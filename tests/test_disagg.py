"""Disaggregated prefill/decode pools + tiered host-offload KV cache.

The load-bearing claims (ISSUE 17 acceptance):

- **Bit-exact prefill→decode handoff** — a disaggregated fleet serves the
  mixed greedy/sampled workload token-identical to the symmetric fleet
  (which equals each request's solo decode), across paged f32 AND int8
  caches, and with a decode-replica kill racing the handoffs (the
  handed-off request migrates AGAIN off the dead adopter's journal).
- **Journal grammar** (satellite) — ``snap`` records carry a ``why``
  (``"failure"`` vs ``"handoff"``), the terminal ``handoff`` event makes
  the SOURCE journal never re-admit a handed-off request, and journals
  written before the field (``why`` stripped) still recover identically.
- **Async prefetch** — a routing-time affinity hit on a host-resident
  prefix starts the upload AT SUBMIT; a request boarding before the
  upload completes must BLOCK (never read half-uploaded rows) and its
  final stream equals the solo decode.
- **Analyzer drift == 0** — ``predict_host_kv_bytes`` /
  ``predict_transfer_bytes`` equal the live host-tier gauges on every
  tick of a disaggregated+offload run, observed on at least the
  mid-handoff, post-demote and prefetch-in-flight shapes.
- **Scenario gates, both sides pinned** — disagg TTFT p95 beats the
  symmetric fleet on the prefill-heavy mix; the host tier's prefix-hit
  blocks strictly exceed the HBM-only fleet's under cache churn; the
  decode-replica kill mid-handoff still completes everything.
"""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.analysis.programs import (
    engine_spec,
    predict_host_kv_bytes,
    predict_transfer_bytes,
)
from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_cached_decoder,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.resilience.scenarios import (
    SCENARIOS,
    run_scenario,
)
from simple_distributed_machine_learning_tpu.serve import (
    RequestJournal,
    ServeFleet,
    ServeSupervisor,
    engine_factory,
)
from simple_distributed_machine_learning_tpu.serve.flight import (
    FlightRecorder,
)
from simple_distributed_machine_learning_tpu.serve.journal import (
    read_journal,
    recover_state,
)
from simple_distributed_machine_learning_tpu.serve.request import DONE

CFG = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
_STAGES = None


def _model():
    global _STAGES
    if _STAGES is None:
        _STAGES = make_gpt_stages(jax.random.key(0), CFG, 2)[0]
    return _STAGES, [s.params for s in _STAGES]


def _solo(stages, params, prompt, n_new, seed, temperature=0.0, top_k=None):
    dec = make_cached_decoder(stages, CFG, len(prompt), n_new,
                              temperature=temperature, top_k=top_k)
    out = dec(params, np.asarray(prompt, np.int32)[None],
              jax.random.key(seed))
    return np.asarray(out)[0, len(prompt):]


def _prompt(n, seed):
    return np.asarray(
        jax.random.randint(jax.random.key(seed), (n,), 0, CFG.vocab),
        np.int32)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.uninstall()
    yield
    faults.uninstall()


def _fleet(tmp_path, name, n_replicas=3, engine_kw=None, **fleet_kw):
    stages, _ = _model()
    kw = dict(engine_kw or {})
    kw.setdefault("n_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 3)
    return ServeFleet(engine_factory(stages, CFG, **kw),
                      os.path.join(str(tmp_path), name),
                      n_replicas=n_replicas, journal_sync=False,
                      **fleet_kw)


_SPECS = [
    dict(prompt_seed=1, prompt_len=5, max_new_tokens=8, seed=11),
    dict(prompt_seed=2, prompt_len=9, max_new_tokens=6, seed=12,
         temperature=0.8, top_k=5),
    dict(prompt_seed=3, prompt_len=3, max_new_tokens=7, seed=13),
    dict(prompt_seed=4, prompt_len=7, max_new_tokens=5, seed=14,
         temperature=1.1, top_k=4),
]


def _fixed_run(tmp_path, name, chaos, **fleet_kw):
    """The mixed greedy/sampled workload over a 3-replica fleet —
    symmetric or disaggregated, optionally under chaos. Returns the
    fleet and each request's final tokens in rid order."""
    if chaos:
        faults.install(faults.FaultPlan.parse(chaos))
    fleet = _fleet(tmp_path, name, **fleet_kw)
    handles = []
    for s in _SPECS:
        s = dict(s)
        prompt = _prompt(s.pop("prompt_len"), s.pop("prompt_seed"))
        handles.append(fleet.submit(prompt, **s))
    fleet.drain()
    fleet.close()
    faults.uninstall()
    return fleet, [list(h.tokens) for h in handles]


# ---------------------------------------------------------------------------
# bit-exact prefill->decode handoff


def test_handoff_bitexact_vs_symmetric_f32(tmp_path):
    """THE tentpole pin (paged f32, greedy + sampled): every request
    crosses the prefill->decode handoff and its stream equals the
    symmetric single-pool fleet's — which equals the solo decode."""
    stages, params = _model()
    _, base = _fixed_run(tmp_path / "sym", "b", None)
    fleet, moved = _fixed_run(tmp_path / "dis", "d", None,
                              prefill_replicas=1)
    assert fleet.disaggregated and fleet.handoffs == len(_SPECS)
    assert {r.role for r in fleet.replicas} == {"prefill", "decode"}
    assert moved == base
    for toks, s in zip(moved, _SPECS):
        np.testing.assert_array_equal(
            toks, _solo(stages, params,
                        _prompt(s["prompt_len"], s["prompt_seed"]),
                        s["max_new_tokens"], s["seed"],
                        temperature=s.get("temperature", 0.0),
                        top_k=s.get("top_k")))
    assert all(r.state == DONE for r in fleet.requests.values())


def test_handoff_bitexact_vs_symmetric_int8(tmp_path):
    """The quantized twin: int8 paged caches hand off bit-exact too (the
    snapshot replays tokens, not cache bytes, so the adopted stream's
    quantization state is rebuilt identically)."""
    kw = dict(cache_dtype="int8")
    _, base = _fixed_run(tmp_path / "sym", "b", None, engine_kw=kw)
    fleet, moved = _fixed_run(tmp_path / "dis", "d", None,
                              prefill_replicas=1, engine_kw=kw)
    assert fleet.handoffs == len(_SPECS)
    assert moved == base


def test_handoff_racing_replica_loss_bitexact(tmp_path):
    """A decode replica dies while handoffs are landing on it: the
    handed-off requests migrate AGAIN off the dead adopter's journal
    (the handoff snap made it self-contained) and every stream still
    equals the symmetric uninterrupted run's."""
    _, base = _fixed_run(tmp_path / "sym", "b", None)
    fleet, moved = _fixed_run(tmp_path / "dis", "d",
                              "replica-kill@fleet.tick=3,rank=1",
                              prefill_replicas=1)
    assert fleet.handoffs >= len(_SPECS)          # every request moved
    assert fleet.replica_losses == 1 and fleet.migrations >= 1
    assert moved == base


# ---------------------------------------------------------------------------
# journal grammar (satellite): snap why + terminal handoff + tolerance


def test_handoff_journal_grammar_and_old_journal_tolerance(tmp_path):
    """Three pins on one run's journals: (1) the SOURCE journal's
    terminal ``handoff`` event means recovery never re-admits a
    handed-off request (no double-serve if the prefill replica dies
    later); (2) the adopter's snap records say ``why: handoff`` (vs
    ``failure`` for loss migration); (3) stripping ``why`` — the
    pre-field journal format — recovers byte-identically modulo the
    cause annotation."""
    fleet, _ = _fixed_run(tmp_path, "g", None, prefill_replicas=1)
    src_path = fleet.replicas[0].journal_path          # the prefill pool
    events, _ = read_journal(src_path)
    handoffs = [e for e in events if e["ev"] == "handoff"]
    assert len(handoffs) == len(_SPECS)
    assert recover_state(events) == {}      # terminal: nothing re-admits

    # the adopters' journals carry the cause
    snaps = []
    for rep in fleet.replicas[1:]:
        evs, _ = read_journal(rep.journal_path)
        snaps += [e for e in evs if e["ev"] == "snap"]
    assert snaps and all(e["why"] == "handoff" for e in snaps)
    rec = recover_state(snaps + [])
    assert all(r.snap_reason == "handoff" for r in rec.values())

    # reason-less old journals: strip the field, recovery still parses
    # and carries the same streams (snap_reason degrades to None)
    stripped = [{k: v for k, v in e.items() if k != "why"} for e in snaps]
    old = recover_state(stripped)
    assert set(old) == set(rec)
    for rid in rec:
        assert list(old[rid].tokens) == list(rec[rid].tokens)
        assert old[rid].snap_reason is None


def test_failure_migration_snap_says_failure(tmp_path):
    """The other half of the cause split: a plain (symmetric) replica
    loss stamps ``why: failure`` on the adoption snaps."""
    fleet, _ = _fixed_run(tmp_path, "f", "replica-kill@fleet.tick=3")
    assert fleet.replica_losses == 1 and fleet.migrations >= 1
    whys = []
    for rep in fleet.replicas:
        if not os.path.exists(rep.journal_path):
            continue
        evs, _ = read_journal(rep.journal_path)
        whys += [e["why"] for e in evs if e["ev"] == "snap"]
    assert whys and set(whys) == {"failure"}


# ---------------------------------------------------------------------------
# async prefetch: routing-time start, boarding blocks until the upload lands


def _offload_fleet(tmp_path, name, n_replicas=1, prefill_replicas=0,
                   prefetch_ticks=3):
    return _fleet(tmp_path, name, n_replicas=n_replicas,
                  prefill_replicas=prefill_replicas,
                  engine_kw=dict(n_slots=2, block_size=4, n_blocks=6,
                                 max_len=24, prefill_chunk=4,
                                 host_cache_blocks=8,
                                 prefetch_ticks=prefetch_ticks))


def test_prefetch_on_affinity_hit_starts_at_submit_and_blocks_boarding(
        tmp_path):
    """The satellite pin: demote a hot prefix to host, re-submit a
    request carrying it — the upload starts AT routing time (in-flight
    blocks visible before any tick), the request does NOT board while
    the upload flies, and once landed its stream equals the solo decode
    (a stale read would diverge)."""
    stages, params = _model()
    fleet = _offload_fleet(tmp_path, "p", prefetch_ticks=3)
    pool = fleet.replicas[0].supervisor.pool
    # 9 tokens: positions 0..7 are cacheable full blocks (the last prompt
    # token always decodes live), so TWO blocks register and demote
    p = _prompt(9, 1)

    # 1) register the prefix in HBM, then churn it out with a
    #    prefix-less scan that needs the whole pool
    fleet.submit(p, max_new_tokens=4, seed=21)
    fleet.drain()
    fleet.submit(_prompt(16, 7), max_new_tokens=8, seed=22)
    fleet.drain()
    st = pool.stats()
    assert st["host_demotes_total"] >= 2    # the prefix lives on host now
    assert pool.host_prefix_len(p) == 8 and pool.shared_prefix_len(p) == 0

    # 2) routing-time prefetch: in flight BEFORE any tick runs
    h = fleet.submit(p, max_new_tokens=4, seed=23)
    st = pool.stats()
    assert st["host_prefetch_hits_total"] == 1
    assert st["host_inflight_blocks"] == 2

    # 3) boarding blocks while the upload flies (prefetch_ticks=3): after
    #    one tick the request has NOT seated and emitted nothing
    fleet.step()
    assert h.slot is None and not h.tokens
    assert pool.stats()["host_inflight_blocks"] == 2
    assert pool.prefetch_blocked(h)

    # 4) drain: the upload lands, the request boards as a prefix HIT on
    #    the promoted blocks and the stream equals the solo decode
    fleet.drain()
    fleet.close()
    st = pool.stats()
    assert st["host_promotes_total"] == 2
    assert st["host_inflight_blocks"] == 0
    np.testing.assert_array_equal(
        h.tokens, _solo(stages, params, p, 4, 23))


def test_prefetch_misses_are_counted_not_fatal(tmp_path):
    """A prompt with no host-resident prefix past the device registry is
    a MISS: counted, no upload, boarding unaffected."""
    fleet = _offload_fleet(tmp_path, "m")
    pool = fleet.replicas[0].supervisor.pool
    assert pool.prefetch(_prompt(8, 9)) is False
    assert pool.stats()["host_prefetch_misses_total"] == 1
    h = fleet.submit(_prompt(8, 9), max_new_tokens=2, seed=31)
    fleet.drain()
    fleet.close()
    assert h.state == DONE and len(h.tokens) == 2


# ---------------------------------------------------------------------------
# analyzer host-tier predictions: drift == 0 on every observed shape


def test_host_tier_analyzer_drift_zero_across_shapes(tmp_path):
    """``predict_host_kv_bytes`` / ``predict_transfer_bytes`` equal the
    live gauges on EVERY tick of a disaggregated+offload run — and the
    run demonstrably passes through all three required shapes:
    mid-handoff, post-demote, and prefetch-in-flight."""
    fleet = _offload_fleet(tmp_path, "a", n_replicas=2,
                           prefill_replicas=1, prefetch_ticks=2)
    seen = {"mid_handoff": False, "post_demote": False,
            "prefetch_inflight": False}

    def check():
        for rep in fleet.replicas:
            if not rep.alive:
                continue
            pool = rep.supervisor.pool
            spec = engine_spec(rep.supervisor.engine)
            st = pool.stats()
            assert predict_host_kv_bytes(spec, st["host_blocks"]) \
                == st["host_bytes_resident"]
            moves = (st["host_demotes_total"] + st["host_promotes_total"])
            assert predict_transfer_bytes(spec, moves) \
                == st["host_transfer_bytes_total"]
            if st["host_demotes_total"]:
                seen["post_demote"] = True
            if st["host_inflight_blocks"]:
                seen["prefetch_inflight"] = True

    def run(submits):
        last = fleet.handoffs
        for prompt, max_new, seed in submits:
            fleet.submit(prompt, max_new_tokens=max_new, seed=seed)
            check()
        while fleet.busy:
            fleet.step()
            if fleet.handoffs > last:
                seen["mid_handoff"] = True
                last = fleet.handoffs
            check()

    p = _prompt(8, 1)
    run([(p, 4, 41)])                           # registers the prefix
    run([(_prompt(16, 7), 8, 42)])              # churns it out -> demote
    run([(p, 4, 43)])                           # prefetch-in-flight
    fleet.close()
    assert fleet.handoffs >= 3
    assert all(seen.values()), seen


# ---------------------------------------------------------------------------
# flight-recorder rows (satellite): pool role + host-tier stats per tick


def test_flight_rows_carry_pool_role_and_host_stats(tmp_path):
    """Per-tick forensics rows stamp which pool the replica serves and
    the full host-tier stats block — a post-mortem can tell WHERE a
    request was and what the offload tier held that tick."""
    fleet = _offload_fleet(tmp_path, "fl", n_replicas=2,
                           prefill_replicas=1)
    for rep in fleet.replicas:
        rep.supervisor.flight = FlightRecorder()
    fleet.submit(_prompt(8, 1), max_new_tokens=4, seed=51)
    fleet.submit(_prompt(16, 7), max_new_tokens=6, seed=52)
    fleet.drain()
    fleet.close()
    roles = {}
    for rep in fleet.replicas:
        rows = rep.supervisor.flight.rows()
        assert rows
        for row in rows:
            assert row["pool_role"] == rep.role
            assert "host_blocks" in row["blocks"]
            assert "host_inflight_blocks" in row["blocks"]
        roles[rep.role] = True
    assert set(roles) == {"prefill", "decode"}


# ---------------------------------------------------------------------------
# scenario gates — exact virtual-clock numbers, BOTH sides pinned


def test_disagg_prefill_heavy_scenario_pinned():
    """The headline TTFT gate: on the bursty prefill-heavy mix the 2+2
    disaggregated fleet's interactive TTFT p95 beats the same-size
    symmetric fleet's by ~2.8x — exact numbers on the virtual clock."""
    stages, _ = _model()
    rep = run_scenario("disagg-prefill-heavy", stages, CFG)
    assert rep["slo_ok"] and rep["completed"] == 16
    assert rep["fleet"]["prefill_replicas"] == 2
    assert rep["fleet"]["handoffs"] == 16
    assert rep["slo"]["interactive"]["ttft_ms_p95"] == 74.719

    sym = dataclasses.replace(SCENARIOS["disagg-prefill-heavy"],
                              name="disagg-symmetric",
                              prefill_replicas=0, min_handoffs=0)
    base = run_scenario(sym, stages, CFG)
    assert base["completed"] == 16
    assert base["slo"]["interactive"]["ttft_ms_p95"] == 206.719
    assert rep["slo"]["interactive"]["ttft_ms_p95"] * 2 \
        < base["slo"]["interactive"]["ttft_ms_p95"]


def test_offload_churn_scenario_pinned(tmp_path):
    """The headline offload gate: under hot-prefix churn the host tier's
    prefix-hit blocks STRICTLY exceed the HBM-only fleet's, with the
    demote/promote/prefetch cycle pinned exactly — plus the gateable
    record and the metric-catalog HELP lines CI re-asserts."""
    stages, _ = _model()
    rep = run_scenario("offload-churn", stages, CFG, outdir=str(tmp_path))
    assert rep["slo_ok"] and rep["completed"] == 24
    ht = rep["host_tier"]
    assert ht == {"host_cache_blocks": 12, "demotes": 66, "promotes": 5,
                  "prefetch_hits": 3, "prefetch_misses": 0,
                  "host_evictions": 53, "transfer_bytes": 145408}

    recs = [json.loads(ln) for ln in open(tmp_path / "metrics.jsonl")]
    serve = [r for r in recs if r.get("kind") == "serve"][-1]
    assert serve["prefix_hit_blocks"] == 16
    assert serve["host_demotes"] == 66 and serve["host_promotes"] == 5
    assert serve["host_transfer_bytes"] == 145408
    assert serve["kv_drift_bytes"] == 0
    prom = open(tmp_path / "metrics.prom").read()
    for name in ("serve_host_blocks", "serve_host_bytes_resident",
                 "serve_host_inflight_blocks", "serve_host_demotes_total",
                 "serve_host_promotes_total", "serve_host_evictions_total",
                 "serve_host_prefetch_hits_total",
                 "serve_host_prefetch_misses_total",
                 "serve_host_transfer_bytes_total"):
        assert f"# HELP {name}" in prom, name

    hbm = dataclasses.replace(SCENARIOS["offload-churn"],
                              name="offload-hbm-only", host_cache_blocks=0,
                              min_host_demotes=0, min_host_prefetch_hits=0)
    base = run_scenario(hbm, stages, CFG, outdir=str(tmp_path / "hbm"))
    assert base["completed"] == 24 and "host_tier" not in base
    recs = [json.loads(ln)
            for ln in open(tmp_path / "hbm" / "metrics.jsonl")]
    bserve = [r for r in recs if r.get("kind") == "serve"][-1]
    assert bserve["prefix_hit_blocks"] == 10     # strictly below 16
    assert serve["prefix_hit_blocks"] > bserve["prefix_hit_blocks"]


def test_handoff_replica_loss_scenario_pinned(tmp_path):
    """The chaos drill: a decode replica dies at fleet tick 6 with
    handoffs in flight — everything completes, the loss migrates, the
    handoff counter and catalog rows land in the gateable artifacts."""
    stages, _ = _model()
    rep = run_scenario("handoff-replica-loss", stages, CFG,
                       outdir=str(tmp_path))
    assert rep["slo_ok"] and rep["completed"] == 16
    assert rep["fleet"]["prefill_replicas"] == 1
    assert rep["fleet"]["handoffs"] == 16
    assert rep["fleet"]["replica_losses"] == 1
    assert rep["fleet"]["migrations"] >= 1
    recs = [json.loads(ln) for ln in open(tmp_path / "metrics.jsonl")]
    serve = [r for r in recs if r.get("kind") == "serve"][-1]
    assert serve["fleet_handoffs"] == 16
    assert serve["pools"]["prefill"]["replicas"] == 1
    prom = open(tmp_path / "metrics.prom").read()
    assert "serve_fleet_handoffs_total 16" in prom
    for name in ("serve_fleet_handoffs_total", "serve_pool_replicas",
                 "serve_pool_queue_depth", "serve_pool_slots_active"):
        assert f"# HELP {name}" in prom, name
    whys = set()
    for p in tmp_path.glob("journal-handoff-replica-loss-r*.jsonl"):
        evs, _ = read_journal(str(p))
        whys |= {e["why"] for e in evs if e["ev"] == "snap"}
    assert whys == {"handoff", "failure"}


def test_handoff_gate_requires_handoffs():
    """The vacuous-pass guard: the disagg scenario with its pools
    flattened must FAIL its gate (min_handoffs unmet), not pass because
    nothing moved — and min_handoffs without pools is refused outright."""
    from simple_distributed_machine_learning_tpu.resilience.scenarios import (
        Scenario,
    )

    stages, _ = _model()
    # flattening the pools while keeping the gate is refused outright
    with pytest.raises(ValueError, match="min_handoffs"):
        dataclasses.replace(SCENARIOS["handoff-replica-loss"],
                            name="no-pools", prefill_replicas=0,
                            chaos=None, min_migrations=0)
    # and a gate the run cannot meet fails slo_ok instead of passing
    starved = dataclasses.replace(SCENARIOS["handoff-replica-loss"],
                                  name="starved", chaos=None,
                                  min_migrations=0, min_handoffs=17)
    rep = run_scenario(starved, stages, CFG)
    assert rep["completed"] == 16           # nothing wrong with the run
    assert rep["fleet"]["handoffs"] == 16   # one short of the gate
    assert not rep["slo_ok"]                # the gate caught it
    with pytest.raises(ValueError, match="min_handoffs"):
        Scenario(name="x", description="", sim=SCENARIOS["steady"].sim,
                 replicas=2, min_handoffs=1)
    with pytest.raises(ValueError, match="min_host_demotes"):
        Scenario(name="x", description="", sim=SCENARIOS["steady"].sim,
                 min_host_demotes=1)


# ---------------------------------------------------------------------------
# bench + CLI surface


def test_bench_disaggregation_row():
    """The bench comparison rows exist and their deterministic fields
    pin: every request hands off exactly once, both fleets complete
    everything (the latency gap itself is gated in the virtual-clock
    scenario, not on wall time)."""
    from bench import _measure_disaggregation

    stages, _ = _model()
    [row] = _measure_disaggregation(stages, CFG, n_requests=8, max_new=8,
                                    prompt_lens=(8, 12), block_size=4)
    assert row["config"] == "gpt_serve_disagg_prefill_decode"
    assert row["handoffs"] == 8
    assert row["completed"] == 8 and row["completed_symmetric"] == 8
    assert row["ttft_ms_p95"] > 0 and row["ttft_ms_p95_symmetric"] > 0


def test_bench_host_offload_row():
    """The host-offload bench row: with the tier the churned prefix
    survives as host hits; the HBM-only fleet re-prefills from scratch
    (counter-based, so exact despite wall-clock timing)."""
    from bench import _measure_host_offload

    stages, _ = _model()
    [row] = _measure_host_offload(stages, CFG, n_requests=8, block_size=4)
    assert row["config"] == "gpt_serve_host_offload_prefix"
    assert row["prefix_hit_blocks"] == 6
    assert row["prefix_hit_blocks_hbm_only"] == 0
    assert row["host_demotes"] == 24 and row["host_promotes"] == 6
    assert row["host_prefetch_hits"] == 3
    assert row["host_transfer_bytes"] == 61440


def test_serve_disagg_cli(tmp_path, capsys):
    """--serve-prefill-replicas / --serve-host-blocks end to end: the
    disaggregated fleet serves the sim, the handoff/pool/host blocks
    land in stdout, the metrics record and the Prom exposition."""
    from simple_distributed_machine_learning_tpu.cli import main

    tele = str(tmp_path / "tele")
    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--serve-sim", "6", "--serve-rate", "100", "--serve-slots", "2",
          "--serve-max-new", "4", "--serve-block-size", "4",
          "--serve-prefill-chunk", "3", "--serve-replicas", "3",
          "--serve-prefill-replicas", "1", "--serve-host-blocks", "8",
          "--telemetry-dir", tele])
    out = capsys.readouterr().out
    assert "| serve: 6/6 requests completed" in out
    assert "disaggregated 1 prefill + 2 decode" in out
    assert "prefill->decode handoff(s)" in out
    assert "host tier" in out
    recs = [json.loads(ln) for ln in
            open(os.path.join(tele, "metrics.jsonl"))]
    r = [x for x in recs if x.get("kind") == "serve"][-1]
    assert r["completed"] == 6 and r["fleet_handoffs"] == 6
    assert r["pools"]["prefill"]["replicas"] == 1
    assert r["pools"]["decode"]["replicas"] == 2
    assert "host_blocks" in r
    prom = open(os.path.join(tele, "metrics.prom")).read()
    assert "serve_fleet_handoffs_total 6" in prom


def test_serve_disagg_cli_flag_validation():
    from simple_distributed_machine_learning_tpu.cli import main

    base = ["--rank", "0", "--world_size", "1", "--model", "gpt",
            "--serve-sim", "2"]
    with pytest.raises(SystemExit, match="needs"):
        main(base + ["--serve-prefill-replicas", "1"])
    with pytest.raises(SystemExit, match="at least one decode"):
        main(base + ["--serve-replicas", "2",
                     "--serve-prefill-replicas", "2"])
    with pytest.raises(SystemExit, match="autoscale"):
        main(base + ["--serve-replicas", "3", "--serve-autoscale", "2,4",
                     "--serve-prefill-replicas", "1"])
    with pytest.raises(SystemExit, match="host-blocks"):
        main(base + ["--serve-host-blocks", "-1"])
    with pytest.raises(SystemExit, match="prefetch-ticks"):
        main(base + ["--serve-host-blocks", "4",
                     "--serve-prefetch-ticks", "0"])
