"""Ulysses all-to-all sequence parallelism == dense causal attention."""

import jax
import jax.numpy as jnp
import numpy as np

from simple_distributed_machine_learning_tpu.parallel.compat import (
    shard_map,
)
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from simple_distributed_machine_learning_tpu.ops.attention import (
    causal_attention,
    mha_init,
)
from simple_distributed_machine_learning_tpu.parallel.sequence import (
    ulysses_attention,
)


def _sharded(fn, mesh, h):
    return jax.jit(shard_map(
        lambda p, xx: fn(p, xx, h, "seq"),
        mesh=mesh, in_specs=(P(), P(None, "seq", None)),
        out_specs=P(None, "seq", None)))


def test_ulysses_matches_full():
    key = jax.random.key(0)
    b, t, d, h = 2, 32, 16, 4
    n_seq = 4
    params = mha_init(key, d, h)
    x = jax.random.normal(jax.random.key(1), (b, t, d))
    mesh = Mesh(np.array(jax.devices()[:n_seq]), ("seq",))
    got = _sharded(ulysses_attention, mesh, h)(params, x)
    want = causal_attention(params, x, h)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_grads_match_full():
    key = jax.random.key(2)
    b, t, d, h = 1, 16, 8, 2
    params = mha_init(key, d, h)
    x = jax.random.normal(jax.random.key(3), (b, t, d))
    mesh = Mesh(np.array(jax.devices()[:2]), ("seq",))

    def sp_loss(p, xx):
        f = shard_map(lambda pp, v: ulysses_attention(pp, v, h, "seq"),
                          mesh=mesh, in_specs=(P(), P(None, "seq", None)),
                          out_specs=P(None, "seq", None))
        return jnp.sum(f(p, xx) ** 2)

    def dense_loss(p, xx):
        return jnp.sum(causal_attention(p, xx, h) ** 2)

    gs = jax.grad(sp_loss, argnums=(0, 1))(params, x)
    gd = jax.grad(dense_loss, argnums=(0, 1))(params, x)
    for a, b_ in zip(jax.tree.leaves(gs), jax.tree.leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=1e-4, atol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    params = mha_init(jax.random.key(4), 16, 2)  # 2 heads, 4-way axis
    x = jax.random.normal(jax.random.key(5), (1, 32, 16))
    mesh = Mesh(np.array(jax.devices()[:4]), ("seq",))
    with pytest.raises(ValueError, match="not divisible"):
        _sharded(ulysses_attention, mesh, 2)(params, x)
