"""Static analyzer (analysis/): seeded defects flag, shipping steps pass.

Three contracts pin the preflight gate:

1. every seeded-defect fixture (one per rule family) produces a finding of
   its family — the analyzer can actually see the defect classes it claims;
2. the EXACT train/eval steps of every shipping model/schedule combination
   analyze clean — the gate never cries wolf on a good launch;
3. the PR-2 caveat is machine-checked: the branch-divergent ring shape that
   deadlocks old XLA:CPU (ring attention inside a >= 2-stage pipeline's
   stage switch) is flagged, and the 1-stage CPU fallback analyzes clean.

Everything here is trace-only (ShapeDtypeStructs): no collective ever runs,
which is the point — the deadlock shape is ANALYZED on the same CPU backend
it would hang.
"""

import jax
import pytest

from simple_distributed_machine_learning_tpu.analysis import (
    Severity,
    abstractify,
    analyze,
)
from simple_distributed_machine_learning_tpu.analysis.fixtures import FIXTURES
from simple_distributed_machine_learning_tpu.analysis.preflight import (
    validate_tp_overlap,
)
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import (
    make_eval_step,
    make_train_step,
)


def _abstract(pipe, batch, in_dim):
    import numpy as np
    x = jax.ShapeDtypeStruct((batch, in_dim), np.float32)
    t = jax.ShapeDtypeStruct((batch,) + pipe.out_shape[:-1], np.int32)
    key = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
    return x, t, key


def _train_report(pipe, batch, in_dim, opt=None):
    opt = opt or sgd(0.1, momentum=0.5)
    buf = abstractify(pipe.init_params())
    state = jax.eval_shape(opt.init, buf)
    x, t, key = _abstract(pipe, batch, in_dim)
    return analyze(make_train_step(pipe, opt), buf, state, x, t, key,
                   mesh=pipe.mesh)


# ---- 1. seeded defects MUST flag ----------------------------------------

@pytest.mark.parametrize("name", [
    "partial_ppermute", "dropped_grad_sync", "wrong_axis_name",
    "bf16_psum_accumulator", "read_after_donate",
    "oob_block_table", "cow_read_after_donate", "unmemoized_retrace",
    "dropped_gather_before_use",
    "kernel_oob_index_map", "kernel_grid_race", "kernel_bad_tile",
    "kernel_f16_accumulator",
])
def test_seeded_defect_is_flagged(name):
    fx = FIXTURES[name]
    assert fx.defect
    report = fx.build()
    fams = {f.family for f in report.findings}
    assert fx.family in fams, (
        f"{name}: expected a {fx.family} finding, got {report.format()}")
    # the CLI's fixture mode exits non-zero on these (fail_on=warning)
    assert not report.ok(fail_on="warning")


def test_seeded_defect_severities():
    # the four hard defects are ERRORs (they gate --lint preflights);
    # dtype drift is a WARNING (a deliberate bf16 run must still launch)
    assert FIXTURES["partial_ppermute"].build().errors
    assert FIXTURES["dropped_grad_sync"].build().errors
    assert FIXTURES["wrong_axis_name"].build().errors
    assert FIXTURES["read_after_donate"].build().errors
    drift = FIXTURES["bf16_psum_accumulator"].build()
    assert not drift.errors and drift.warnings
    rules = {f.rule for f in drift.findings}
    assert "dtype-drift.low-precision-reduction" in rules
    assert "dtype-drift.low-precision-carry" in rules


def test_new_family_defect_severities():
    # the serve-path defect classes are all ERRORs: silent K/V corruption,
    # device use-after-free and unmemoized recompiles must gate --lint
    for name in ("oob_block_table", "cow_read_after_donate",
                 "unmemoized_retrace", "dropped_gather_before_use"):
        assert FIXTURES[name].build().errors, name
    # inside the kernel box: a provably-escaping index map and a parallel
    # write race are ERRORs; tiling waste and a sub-f32 scratch accumulator
    # are WARNINGs (real, but an autotuner candidate may accept them)
    for name in ("kernel_oob_index_map", "kernel_grid_race"):
        assert FIXTURES[name].build().errors, name
    for name in ("kernel_bad_tile", "kernel_f16_accumulator"):
        report = FIXTURES[name].build()
        assert not report.errors and report.warnings, report.format()


def test_clean_fixtures_pass():
    for name in ("clean_grad_sync", "clean_pipeline_step",
                 "clean_cow_tick", "clean_gather_before_use",
                 "kernel_clean_paged", "kernel_clean_grid",
                 "kernel_packed_tile", "kernel_f32_accumulator"):
        report = FIXTURES[name].build()
        assert report.ok(fail_on="warning"), report.format()


def test_sharded_state_vary_threads_through_cond_and_while():
    """Declared ``vary=`` contracts must survive cond/switch and while
    sub-jaxpr boundaries, whose invars are NOT arity-identical to the
    eqn's (branches drop the predicate; while's two jaxprs each see their
    own consts + the carry). The dropped-gather defect wrapped in a
    lax.cond used to analyze vacuously clean — a certified-clean report
    for a silently-diverging-params program."""
    import numpy as np
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from simple_distributed_machine_learning_tpu.analysis import spec
    from simple_distributed_machine_learning_tpu.analysis.fixtures import (
        _mesh,
    )
    from simple_distributed_machine_learning_tpu.parallel.compat import (
        shard_map,
    )

    mesh = _mesh(4)

    def _inner(reduced):
        def step(w, m, g):
            m2 = 0.9 * m + g
            if reduced:
                m2 = lax.pmean(m2, "data")
            return w - 0.1 * m2, m2
        return shard_map(step, mesh=mesh, in_specs=(P(), P(), P()),
                         out_specs=(P(), P()), check_vma=False)

    w = jax.ShapeDtypeStruct((16, 4), jax.numpy.float32)
    g = jax.ShapeDtypeStruct((16, 4), jax.numpy.float32)
    m = spec((16, 4), np.float32, vary=("data",))
    pred = jax.ShapeDtypeStruct((), jax.numpy.bool_)

    def behind_cond(reduced):
        inner = _inner(reduced)
        return lambda p, w, m, g: lax.cond(
            p, inner, lambda w, m, g: (w, m), w, m, g)

    def behind_while(w_, m_, g_):
        inner = _inner(False)
        def body(c):
            i, cw, cm = c
            nw, nm = inner(cw, cm, g_)
            return i + 1, nw, nm
        return lax.while_loop(lambda c: c[0] < 3, body, (0, w_, m_))

    def rules(report):
        return {f.rule for f in report.findings}

    assert "sharded-state.missing-gather" in rules(
        analyze(behind_cond(False), pred, w, m, g, mesh=mesh))
    assert "sharded-state.missing-gather" in rules(
        analyze(behind_while, w, m, g, mesh=mesh))
    # the reduced twin stays clean through the same boundary — threading
    # must not invent variance the pmean already retired
    assert not any("sharded-state" in r for r in rules(
        analyze(behind_cond(True), pred, w, m, g, mesh=mesh)))


# ---- 2. shipping model/schedule combos analyze clean --------------------

def _mlp_pipe(schedule, n_stages=2, n_data=2, n_model=1):
    if n_model > 1:
        from simple_distributed_machine_learning_tpu.parallel.tensor import (
            make_mlp_tp_stages,
        )
        dims = [16] * (2 * n_stages) + [10]
        stages, wire, out = make_mlp_tp_stages(jax.random.key(0), dims,
                                               n_stages, n_model)
    else:
        from simple_distributed_machine_learning_tpu.models.mlp import (
            make_mlp_stages,
        )
        stages, wire, out = make_mlp_stages(jax.random.key(0),
                                            [16] * n_stages + [10], n_stages)
    mesh = make_mesh(n_stages=n_stages, n_data=n_data, n_model=n_model,
                     devices=jax.devices()[:n_stages * n_data * n_model])
    return Pipeline(stages, mesh, wire, out, n_microbatches=2,
                    schedule=schedule)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
@pytest.mark.parametrize("n_data", [1, 2])
def test_mlp_pipeline_step_clean(schedule, n_data):
    pipe = _mlp_pipe(schedule, n_data=n_data)
    report = _train_report(pipe, batch=4 * n_data, in_dim=16)
    assert report.ok(fail_on="warning"), report.format()


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_tp_pipeline_step_clean(schedule):
    # dp x pp x tp: the full 3D mesh of the 8-device dryrun
    pipe = _mlp_pipe(schedule, n_stages=2, n_data=2, n_model=2)
    report = _train_report(pipe, batch=8, in_dim=16)
    assert report.ok(fail_on="warning"), report.format()


def test_lenet_pipeline_step_clean():
    from simple_distributed_machine_learning_tpu.models.lenet import (
        make_lenet_stages,
    )
    stages, wire, out = make_lenet_stages(jax.random.key(0), 2)
    mesh = make_mesh(n_stages=2, n_data=2, devices=jax.devices()[:4])
    pipe = Pipeline(stages, mesh, wire, out, n_microbatches=2)
    opt = sgd(0.1, momentum=0.5)
    import numpy as np
    buf = abstractify(pipe.init_params())
    state = jax.eval_shape(opt.init, buf)
    x = jax.ShapeDtypeStruct((8, 28, 28, 1), np.float32)
    t = jax.ShapeDtypeStruct((8,), np.int32)
    key = jax.ShapeDtypeStruct((), jax.random.key(0).dtype)
    report = analyze(make_train_step(pipe, opt), buf, state, x, t, key,
                     mesh=mesh)
    assert report.ok(fail_on="warning"), report.format()


def _gpt_pipe(schedule="gpipe", n_stages=2, n_seq=1, attn="dense"):
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    cfg = GPTConfig(vocab=16, seq_len=8, d_model=16, n_heads=2, n_layers=2,
                    attn_impl=attn, n_seq=n_seq)
    stages, wire, out = make_gpt_stages(jax.random.key(0), cfg, n_stages)
    mesh = make_mesh(n_stages=n_stages, n_data=1, n_seq=n_seq,
                     devices=jax.devices()[:n_stages * n_seq])
    return Pipeline(stages, mesh, wire, out, n_microbatches=2,
                    schedule=schedule), cfg


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_gpt_pipeline_step_clean(schedule):
    pipe, cfg = _gpt_pipe(schedule)
    report = _train_report(pipe, batch=4, in_dim=cfg.seq_len)
    assert report.ok(fail_on="warning"), report.format()


def test_eval_step_clean():
    import numpy as np
    pipe = _mlp_pipe("gpipe", n_data=2)
    buf = abstractify(pipe.init_params())
    x, t, key = _abstract(pipe, 8, 16)
    n_valid = jax.ShapeDtypeStruct((), np.int32)
    report = analyze(make_eval_step(pipe), buf, x, t, key, n_valid,
                     mesh=pipe.mesh)
    assert report.ok(fail_on="warning"), report.format()


def test_cost_report_ranks_dp_grad_allreduce():
    # the dominant collective of a dp=2 train step is the gradient psum the
    # shard_map transpose inserts — the cost table must surface it
    pipe = _mlp_pipe("gpipe", n_data=2)
    report = _train_report(pipe, batch=8, in_dim=16)
    assert report.costs, "cost table empty"
    top = max(report.costs, key=lambda c: c.total_bytes)
    assert top.prim == "psum" and "data" in top.axes


# ---- 3. the PR-2 caveat, machine-checked --------------------------------

def test_ring_in_divergent_branches_flagged():
    """Ring attention inside a >= 2-stage pipeline's stage switch is the
    exact shape that deadlocks old XLA:CPU's global collective-permute
    rendezvous (PR-2 caveat): the analyzer must flag it — as a WARNING
    (portability hazard), not an ERROR (it is correct on TPU ICI)."""
    pipe, cfg = _gpt_pipe(n_stages=2, n_seq=2, attn="ring")
    report = _train_report(pipe, batch=4, in_dim=cfg.seq_len // 2)
    rules = {f.rule for f in report.findings}
    assert "ppermute-deadlock.ring-in-branch" in rules, report.format()
    assert report.ok(fail_on="error"), report.format()


def test_ring_one_stage_fallback_clean():
    """The 1-stage CPU fallback (what cli/tests run on old jax) keeps the
    ring out of any stage switch: must analyze clean."""
    pipe, cfg = _gpt_pipe(n_stages=1, n_seq=2, attn="ring")
    report = _train_report(pipe, batch=4, in_dim=cfg.seq_len // 2)
    deadlock = [f for f in report.findings
                if f.family == "ppermute-deadlock"]
    assert not deadlock, report.format()
    assert report.ok(), report.format()


# ---- preflight spec validation (bench --tp/--overlap routing) -----------

def test_validate_tp_overlap_divisibility():
    from simple_distributed_machine_learning_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab=16, seq_len=8, d_model=16, n_heads=4, n_layers=1)
    errors, _ = validate_tp_overlap(3, "none", 8, cfg)
    assert any("n_heads" in e for e in errors)
    assert any("hidden width" in e for e in errors)
    errors, _ = validate_tp_overlap(16, "none", 8, cfg)
    assert any("devices" in e for e in errors)
    errors, _ = validate_tp_overlap(1, "ring", 8, cfg)
    assert any("ring" in e for e in errors)
    errors, warns = validate_tp_overlap(2, "ring", 8, cfg,
                                        batch=4, n_micro=1)
    assert not errors and not warns
    # d_model=16 splits over tp=2; a tp that does not divide it only
    # degrades the ring to the monolithic psum: warning, not error
    cfg2 = GPTConfig(vocab=16, seq_len=10, d_model=20, n_heads=4,
                     n_layers=1, mlp_ratio=2)
    errors, warns = validate_tp_overlap(4, "ring", 8, cfg2,
                                        batch=6, n_micro=2)
    assert not errors
    assert any("falls back" in w for w in warns)


def test_validate_clean_spec_passes():
    from simple_distributed_machine_learning_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab=16, seq_len=8, d_model=16, n_heads=4, n_layers=1)
    errors, warns = validate_tp_overlap(2, "none", 8, cfg)
    assert not errors and not warns


# ---- CLI exit codes (in-process) ----------------------------------------

def test_cli_fixture_exit_codes():
    from simple_distributed_machine_learning_tpu.analysis.__main__ import main
    assert main(["--fixture", "dropped_grad_sync"]) == 1
    assert main(["--fixture", "clean_grad_sync"]) == 0
    assert main(["--list"]) == 0


def test_cli_dryrun_clean():
    # the CI lint gate's per-config invocation, in-process on the 8 virtual
    # devices the suite already runs under
    from simple_distributed_machine_learning_tpu.analysis.__main__ import main
    assert main(["--dryrun", "2"]) == 0


def test_severity_ordering_and_families():
    assert Severity.ERROR > Severity.WARNING > Severity.INFO
    fams = {fx.family for fx in FIXTURES.values() if fx.defect}
    assert fams == {"ppermute-deadlock", "unreduced-gradient", "mesh-axis",
                    "dtype-drift", "donation", "scatter-bounds",
                    "retrace-explosion", "sharded-state",
                    "kernel-oob", "kernel-race", "kernel-tile",
                    "kernel-dtype-drift", "protocol"}
