"""Stage packing and wire codec round-trips."""

import jax
import jax.numpy as jnp
import numpy as np

from simple_distributed_machine_learning_tpu.parallel.staging import (
    pack_stage_params,
    unpack_stage_params,
    wire_decode,
    wire_encode,
)


def test_pack_unpack_roundtrip_heterogeneous():
    p0 = {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.ones((4,))}
    p1 = [{"w": jnp.full((2, 2), 2.0)}, {"b": jnp.zeros((5,))}]
    buf, metas = pack_stage_params([p0, p1])
    assert buf.shape == (2, 16)  # max(12+4, 4+5) = 16
    r0 = unpack_stage_params(buf[0], metas[0])
    r1 = unpack_stage_params(buf[1], metas[1])
    jax.tree.map(lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                         np.asarray(b)), p0, r0)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(np.asarray(a),
                                                         np.asarray(b)), p1, r1)


def test_wire_roundtrip():
    x = jnp.arange(24.0).reshape(2, 3, 4)  # batch 2, per-sample (3, 4)
    wire = wire_encode(x, 20)
    assert wire.shape == (2, 20)
    back = wire_decode(wire, (3, 4))
    np.testing.assert_allclose(np.asarray(back), np.asarray(x))
    np.testing.assert_allclose(np.asarray(wire[:, 12:]), 0.0)
