"""Pipeline-parallel KV-cache decoding: exact parity with the single-device
cached decoder, from the LIVE packed buffer, across stage counts and dp."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_cached_decoder,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.models.pp_decode import (
    make_pp_decoder,
)
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline

CFG = GPTConfig(vocab=32, seq_len=24, d_model=32, n_heads=2, n_layers=4)


def _setup(n_stages, n_data=1):
    stages, wd, osh = make_gpt_stages(jax.random.key(0), CFG, n_stages)
    mesh = make_mesh(n_stages=n_stages, n_data=n_data,
                     devices=jax.devices()[:n_stages * n_data])
    pipe = Pipeline(stages, mesh, wd, osh, n_microbatches=1)
    return stages, pipe, pipe.init_params()


@pytest.mark.parametrize("n_stages,n_data", [(1, 1), (2, 1), (4, 1), (2, 2)])
def test_pp_decode_matches_cached(n_stages, n_data):
    stages, pipe, buf = _setup(n_stages, n_data)
    prompt = jax.random.randint(jax.random.key(1), (4, 5), 0, CFG.vocab)
    want = make_cached_decoder(stages, CFG, 5, 9)(
        [s.params for s in stages], prompt, jax.random.key(3))
    got = make_pp_decoder(pipe, CFG, 5, 9)(buf, prompt, jax.random.key(3))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pp_decode_bf16_cache_matches_f32():
    """cache_dtype=bf16 through the stage-sharded decoder: the replication
    anchors must not silently promote the carried caches back to f32, and
    greedy tokens must match the f32-cache run on this model."""
    stages, pipe, buf = _setup(2)
    prompt = jax.random.randint(jax.random.key(1), (2, 5), 0, CFG.vocab)
    want = make_pp_decoder(pipe, CFG, 5, 7)(buf, prompt, jax.random.key(3))
    got = make_pp_decoder(pipe, CFG, 5, 7, cache_dtype=jnp.bfloat16)(
        buf, prompt, jax.random.key(3))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pp_decode_sampling_key_stream_matches():
    """temperature + top-k through the pipeline: identical tokens to the
    single-device cached decoder (same one-split-per-token key stream)."""
    stages, pipe, buf = _setup(2)
    prompt = jax.random.randint(jax.random.key(2), (2, 4), 0, CFG.vocab)
    kw = dict(temperature=0.8, top_k=5)
    want = make_cached_decoder(stages, CFG, 4, 8, **kw)(
        [s.params for s in stages], prompt, jax.random.key(11))
    got = make_pp_decoder(pipe, CFG, 4, 8, **kw)(
        buf, prompt, jax.random.key(11))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pp_decode_reads_live_buffer():
    """Decoding from the packed buffer follows training updates."""
    from simple_distributed_machine_learning_tpu.data.text import (
        synthetic_tokens,
    )
    from simple_distributed_machine_learning_tpu.train.optimizer import sgd
    from simple_distributed_machine_learning_tpu.train.step import (
        make_train_step,
    )

    stages, pipe, buf = _setup(2)
    prompt = jax.random.randint(jax.random.key(4), (2, 4), 0, CFG.vocab)
    dec = make_pp_decoder(pipe, CFG, 4, 6)
    out0 = np.asarray(dec(buf, prompt, jax.random.key(0)))
    data = synthetic_tokens(8, CFG.seq_len, CFG.vocab, seed=5)
    opt = sgd(0.5, momentum=0.9)
    state = opt.init(buf)
    step = make_train_step(pipe, opt)
    for i in range(10):
        buf, state, _ = step(buf, state, jnp.asarray(data.x, jnp.float32),
                             jnp.asarray(data.y), jax.random.key(i))
    out1 = np.asarray(dec(buf, prompt, jax.random.key(0)))
    assert not np.array_equal(out0, out1)


def test_pp_decode_validation():
    stages, pipe, buf = _setup(2)
    with pytest.raises(ValueError, match="exceeds the model's sequence"):
        make_pp_decoder(pipe, CFG, 20, 9)
    with pytest.raises(ValueError, match="non-empty prompt"):
        make_pp_decoder(pipe, CFG, 0, 4)


def test_pp_decode_rejects_mismatched_cfg():
    _, pipe, _ = _setup(2)
    wrong = GPTConfig(vocab=32, seq_len=64, d_model=32, n_heads=2,
                      n_layers=4)
    with pytest.raises(ValueError, match="does not match the stages'"):
        make_pp_decoder(pipe, wrong, 4, 4)
