"""Resilience (resilience/): fault injection, checkpoint store, supervisor.

The elastic acceptance pin lives here: a training run killed mid-epoch by
an injected host-loss fault auto-restores the latest VALID checkpoint,
repacks it onto a different stage count, and resumes to completion with
loss continuing from the restored step (vs the uninterrupted run). Plus:
the deterministic fault-plan semantics, the checksum-validated store never
selecting a corrupt checkpoint, write-crash and budget-exhaustion recovery
paths, async-save error surfacing, and bench.py's rc-17 wedged-device
detection with retry + the structured device_unhealthy row.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.data.mnist import Dataset
from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.resilience import (
    CheckpointStore,
    RestartBudgetExceeded,
    RestartPolicy,
    faults,
    make_elastic_trainer,
    supervise,
)
from simple_distributed_machine_learning_tpu.resilience.supervisor import (
    PeerLost,
)
from simple_distributed_machine_learning_tpu.train.trainer import (
    TrainConfig,
    Trainer,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    """Every test starts and ends with no active fault plan."""
    faults.uninstall()
    yield
    faults.uninstall()


# ---------------------------------------------------------------------------
# fault plans


def test_fault_plan_parse_grammar():
    p = faults.FaultPlan.parse(
        "host-kill@train.step=6;"
        "slow-tick@serve.tick,dur=0.01,after=2,times=3;"
        "frozen-peer@watchdog.heartbeat,rank=1")
    kinds = [(s.kind, s.site, s.step, s.rank) for s in p.specs]
    assert kinds == [("host-kill", "train.step", 6, None),
                     ("slow-tick", "serve.tick", None, None),
                     ("frozen-peer", "watchdog.heartbeat", None, 1)]
    assert p.specs[1].dur == 0.01 and p.specs[1].after == 2
    for bad in ("explode@train.step", "host-kill", "host-kill@x,zzz=1",
                "", "host-kill@train.step,dur=-1",
                # a typo'd site must be rejected, not silently never fire
                # (a vacuously-green chaos drill is worse than none)
                "host-kill@train.steps=6"):
        with pytest.raises(ValueError):
            faults.FaultPlan.parse(bad)


def test_fault_step_match_fires_once_and_counts():
    plan = faults.install(faults.FaultPlan.parse("host-kill@train.step=3"))
    assert faults.maybe_fire("train.step", step=2) == []
    with pytest.raises(faults.HostLost):
        faults.maybe_fire("train.step", step=3)
    # times=1 default: the same step on a later attempt does NOT re-fire —
    # that is what lets a supervised retry run clean past the kill point
    assert faults.maybe_fire("train.step", step=3) == []
    assert plan.stats()["total_fired"] == 1


def test_fault_after_times_and_sleep_routing():
    slept = []
    plan = faults.FaultPlan.parse("slow-tick@serve.tick,dur=0.5,after=1,"
                                  "times=2", sleep=slept.append)
    faults.install(plan)
    for i in range(5):
        faults.maybe_fire("serve.tick", step=i)
    assert slept == [0.5, 0.5]          # skipped first, fired twice, capped


def test_fault_noop_without_plan_and_check_has_no_effects():
    assert faults.maybe_fire("train.step", step=0) == []
    faults.install(faults.FaultPlan.parse("host-kill@train.step=0"))
    # check() matches and counts but never raises — the watchdog's entry
    fired = faults.check("train.step", step=0)
    assert [f.kind for f in fired] == ["host-kill"]
    assert faults.check("train.step", step=0) == []   # times exhausted


def test_fault_random_schedule_deterministic():
    a = faults.FaultPlan.random(7, n=4, max_step=50)
    b = faults.FaultPlan.random(7, n=4, max_step=50)
    assert ([(s.kind, s.site, s.step) for s in a.specs]
            == [(s.kind, s.site, s.step) for s in b.specs])
    c = faults.FaultPlan.random(8, n=4, max_step=50)
    assert ([(s.kind, s.step) for s in a.specs]
            != [(s.kind, s.step) for s in c.specs])


def test_install_from_env(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "wedged-device@bench.probe=0")
    plan = faults.install_from_env()
    assert plan is faults.active()
    assert plan.specs[0].kind == "wedged-device"
    monkeypatch.delenv(faults.ENV_VAR)
    faults.uninstall()
    assert faults.install_from_env() is None


# ---------------------------------------------------------------------------
# checkpoint store


def _store_state(seed=0):
    rng = np.random.RandomState(seed)
    return rng.randn(4, 8).astype(np.float32), [rng.randn(4, 8)]


def test_store_save_validate_latest(tmp_path):
    store = CheckpointStore(str(tmp_path), keep=3)
    buf, opt = _store_state()
    for step in (4, 8):
        store.save(buf, opt, step, extra={"epoch": step // 4, "n_stages": 2})
    entries = store.entries()
    assert [e["step"] for e in entries] == [4, 8]
    assert all(store.validate(e) for e in entries)
    latest = store.latest_valid()
    assert latest["step"] == 8 and latest["extra"]["n_stages"] == 2
    assert os.path.exists(latest["path"])


def test_store_never_selects_corrupt_checkpoint(tmp_path, capfd):
    """The acceptance invariant: a corrupt checkpoint is NEVER selected —
    the newest generation is truncated on disk and latest_valid falls back
    to the previous one, loudly."""
    store = CheckpointStore(str(tmp_path), keep=3)
    buf, opt = _store_state()
    store.save(buf, opt, 4, extra={"epoch": 1})
    store.save(buf, opt, 8, extra={"epoch": 2})
    newest = os.path.join(str(tmp_path), store.entries()[-1]["file"])
    with open(newest, "r+b") as f:        # torn write / bad disk
        f.truncate(os.path.getsize(newest) // 2)
    latest = store.latest_valid()
    assert latest["step"] == 4
    assert "skipping corrupt" in capfd.readouterr().err
    # every generation corrupt -> None, not a bad pick
    with open(os.path.join(str(tmp_path), latest["file"]), "wb") as f:
        f.write(b"garbage")
    assert store.latest_valid() is None


def test_store_gc_keeps_newest(tmp_path):
    store = CheckpointStore(str(tmp_path), keep=2)
    buf, opt = _store_state()
    for step in (1, 2, 3, 4):
        store.save(buf, opt, step)
    assert [e["step"] for e in store.entries()] == [3, 4]
    files = {f for f in os.listdir(str(tmp_path)) if f.endswith(".npz")}
    assert files == {"ckpt-00000003.npz", "ckpt-00000004.npz"}


def test_store_resave_same_step_supersedes_and_gc_keeps_live_file(tmp_path):
    """A restarted attempt re-saving a step it already saved (the corrupt-
    newest-generation fallback path) must SUPERSEDE the stale manifest
    entry, and GC must never unlink a file a live entry still references —
    the duplicate-entry case where position-based GC would delete the
    newest valid checkpoint out from under its own manifest line."""
    store = CheckpointStore(str(tmp_path), keep=2)
    buf, opt = _store_state()
    store.save(buf, opt, 4, extra={"epoch": 1})
    store.save(buf, opt, 8, extra={"epoch": 2})
    store.save(buf, opt, 8, extra={"epoch": 2})   # re-run of epoch 2
    entries = store.entries()
    assert [e["step"] for e in entries] == [4, 8]  # one entry per file
    store.save(buf, opt, 12, extra={"epoch": 3})   # triggers GC (keep=2)
    assert [e["step"] for e in store.entries()] == [8, 12]
    latest = store.latest_valid()
    assert latest["step"] == 12
    # the step-8 file survived GC and still validates
    assert store.validate(store.entries()[0])


def test_store_manifest_tolerates_torn_line(tmp_path):
    store = CheckpointStore(str(tmp_path), keep=3)
    buf, opt = _store_state()
    store.save(buf, opt, 4)
    with open(os.path.join(str(tmp_path), "MANIFEST.jsonl"), "a") as f:
        f.write('{"file": "ckpt-trunc')   # crash mid-append
    assert [e["step"] for e in store.entries()] == [4]
    assert store.latest_valid()["step"] == 4


# ---------------------------------------------------------------------------
# elastic supervisor (stub-level semantics)


class _StubTrainer:
    def __init__(self, outcomes, n_stages):
        self._outcomes = outcomes
        self.n_stages = n_stages
        self._step_count = 0
        self.start_epoch = 1
        self.history = []

    def fit(self):
        out = self._outcomes.pop(0)
        if out is not None:
            raise out


def _host_lost():
    return faults.HostLost(
        faults.FaultSpec(kind="host-kill", site="train.step"), "train.step")


def test_supervise_shrinks_on_peer_loss_with_exponential_backoff():
    outcomes = [PeerLost("peer 1 vanished"), _host_lost(), None]
    built, sleeps = [], []

    def build(n):
        built.append(n)
        return _StubTrainer(outcomes, n)

    report = supervise(build, (4, 2, 1),
                       policy=RestartPolicy(max_restarts=3,
                                            base_backoff_s=0.1,
                                            backoff_factor=2.0,
                                            max_backoff_s=10.0),
                       sleep=sleeps.append)
    assert built == [4, 2, 1]            # one rung down per host/peer loss
    assert report["completed"] and report["restarts"] == 2
    assert sleeps == [0.1, 0.2]          # exponential
    assert [t[0] for t in report["transitions"]] == [
        "RUNNING", "RESTORING", "RUNNING", "RESTORING", "RUNNING", "DONE"]


def test_supervise_budget_exhaustion_fails_loudly():
    outcomes = [_host_lost(), _host_lost(), _host_lost()]

    def build(n):
        return _StubTrainer(outcomes, n)

    with pytest.raises(RestartBudgetExceeded):
        supervise(build, (2, 1),
                  policy=RestartPolicy(max_restarts=2, base_backoff_s=0.0),
                  sleep=lambda s: None)


def test_supervise_propagates_real_bugs():
    def build(n):
        return _StubTrainer([ValueError("a real bug")], n)

    with pytest.raises(ValueError, match="a real bug"):
        supervise(build, (1,), sleep=lambda s: None)


# ---------------------------------------------------------------------------
# elastic supervisor (real training, the acceptance pin)


def _tiny_ds():
    rng = np.random.RandomState(0)
    return Dataset(rng.randn(120, 12).astype(np.float32),
                   rng.randint(0, 10, 120))


_DIMS = [12, 16, 14, 16, 10]


def _build_pipe(n):
    stages, wd, od = make_mlp_stages(jax.random.key(0), _DIMS, n)
    return Pipeline(stages, make_mesh(n_stages=n, n_data=1,
                                      devices=jax.devices()[:n]), wd, od)


def test_elastic_host_kill_restores_repacks_and_loss_continues(tmp_path):
    """THE acceptance pin: host-kill at step 6 (mid-epoch 2 of a 4-step-
    per-epoch run) -> the supervisor restores the epoch-1 checkpoint
    (step 4), repacks it from 2 pipeline stages onto 1, and resumes to
    completion — with every post-restore epoch loss matching the
    uninterrupted 2-stage run (identical state => identical trajectory to
    cross-topology float tolerance)."""
    ds = _tiny_ds()
    cfg = TrainConfig(epochs=4, batch_size=30, print_throughput=False)

    ref = Trainer(_build_pipe(2), ds, ds, cfg)
    ref_losses = []
    ref._log_metrics = lambda rec: ref_losses.append(rec["train_loss"])
    ref.fit()

    store = CheckpointStore(str(tmp_path), keep=8)
    faults.install(faults.FaultPlan.parse("host-kill@train.step=6"))
    sleeps = []
    report = supervise(
        lambda n: make_elastic_trainer(_build_pipe, n, store, ds, ds, cfg),
        (2, 1), policy=RestartPolicy(max_restarts=3),
        sleep=sleeps.append)

    assert report["completed"] and report["restarts"] == 1
    a1, a2 = report["attempts"]
    assert (a1["n_stages"], a1["outcome"], a1["fault"]) == (2, "fault",
                                                            "HostLost")
    # the kill hit mid-epoch 2: only epoch 1 finished before it
    assert [h["epoch"] for h in a1["history"]] == [1]
    assert a1["history"][0]["train_loss"] == ref_losses[0]
    # restored the latest valid checkpoint (epoch 1 / step 4), repacked 2->1
    assert a2["n_stages"] == 1
    assert a2["resumed_step"] == 4 and a2["start_epoch"] == 2
    assert a2["outcome"] == "completed"
    # loss CONTINUES from the restored step: epochs 2..4 match the
    # uninterrupted run (cross-stage-count float tolerance, the bound
    # test_checkpoint's repack trajectory test established)
    np.testing.assert_allclose([h["train_loss"] for h in a2["history"]],
                               ref_losses[1:], rtol=3e-5, atol=3e-5)
    assert sleeps == [0.05]
    # the manifest recorded the source topology the repack keyed off
    assert store.latest_valid()["extra"]["n_stages"] == 1
    assert [t[0] for t in report["transitions"]] == [
        "RUNNING", "RESTORING", "RUNNING", "DONE"]


def test_elastic_write_crash_retries_in_place(tmp_path):
    """A checkpoint-write crash is recoverable but NOT topology-shrinking:
    the supervisor restarts at the same stage count; the fault's times=1
    schedule lets the retry save cleanly and complete."""
    ds = _tiny_ds()
    cfg = TrainConfig(epochs=2, batch_size=30, print_throughput=False)
    store = CheckpointStore(str(tmp_path), keep=4)
    faults.install(faults.FaultPlan.parse("ckpt-write-crash@ckpt.write"))
    report = supervise(
        lambda n: make_elastic_trainer(_build_pipe, n, store, ds, ds, cfg),
        (2, 1), policy=RestartPolicy(max_restarts=2),
        sleep=lambda s: None)
    assert report["completed"] and report["restarts"] == 1
    a1, a2 = report["attempts"]
    assert a1["fault"] == "CheckpointWriteCrash"
    assert a2["n_stages"] == 2            # same rung: nothing was lost
    assert store.latest_valid() is not None


def test_elastic_trainer_rejects_checkpoint_dir_config(tmp_path):
    ds = _tiny_ds()
    cfg = TrainConfig(epochs=1, batch_size=30,
                      checkpoint_dir=str(tmp_path / "clash"))
    with pytest.raises(ValueError, match="CheckpointStore"):
        make_elastic_trainer(_build_pipe, 1,
                             CheckpointStore(str(tmp_path)), ds, ds, cfg)


# ---------------------------------------------------------------------------
# async checkpoint error surfacing (satellite)


def test_async_write_crash_surfaces_from_fit(tmp_path, capfd):
    """An async checkpoint write that dies on the writer thread must fail
    the RUN (original exception type, surfaced at the next wait point) —
    not vanish while training reports success with no checkpoint."""
    ds = _tiny_ds()
    cfg = TrainConfig(epochs=2, batch_size=30, print_throughput=False,
                      checkpoint_dir=str(tmp_path), async_checkpoint=True)
    tr = Trainer(_build_pipe(1), ds, ds, cfg)
    faults.install(faults.FaultPlan.parse("ckpt-write-crash@ckpt.write"))
    with pytest.raises(faults.CheckpointWriteCrash):
        tr.fit()
    assert "async write" in capfd.readouterr().err


# ---------------------------------------------------------------------------
# bench: rc-17 wedged-device detection + retry + structured row (satellite)


def _bench():
    sys.path.insert(0, REPO)
    import bench
    return bench


def test_bench_supervised_smoke_retry_then_recover(capsys):
    """First probe wedges (rc 17), the retry succeeds: one backoff sleep,
    True returned, no device_unhealthy row."""
    bench = _bench()
    rcs, sleeps = [17, 0], []
    ok = bench._supervised_smoke(probe=lambda a, t: rcs.pop(0),
                                 backoff_s=3.0, sleep=sleeps.append)
    assert ok and sleeps == [3.0]
    assert "device_unhealthy" not in capsys.readouterr().out


def test_bench_supervised_smoke_emits_device_unhealthy_row(capsys):
    """Persistently wedged: retry once with backoff, then EMIT the
    structured row instead of dying with no measurement."""
    bench = _bench()
    sleeps = []
    ok = bench._supervised_smoke(probe=lambda a, t: 17, backoff_s=2.0,
                                 sleep=sleeps.append)
    assert not ok and sleeps == [2.0]
    rows = [json.loads(line) for line in
            capsys.readouterr().out.strip().splitlines()]
    row = rows[-1]
    assert row["metric"] == "device_unhealthy"
    assert row["rc"] == 17 and row["attempts"] == 2


def test_bench_supervised_smoke_non_wedge_rc_stays_fatal():
    bench = _bench()
    with pytest.raises(SystemExit) as ei:
        bench._supervised_smoke(probe=lambda a, t: 3, sleep=lambda s: None)
    assert ei.value.code == 3


def test_bench_serve_round_records_device_unhealthy(tmp_path, monkeypatch,
                                                    capsys):
    """The r04/r05 stale-baseline fix: a --serve round on a persistently
    wedged device writes the structured device_unhealthy record INTO
    benchmarks/serving.json (and exits clean), so the artifact is never
    silently stale and the next healthy round re-establishes the baseline
    by overwriting it with real rows."""
    bench = _bench()
    (tmp_path / "benchmarks").mkdir()
    monkeypatch.setattr(bench, "REPO", str(tmp_path))
    monkeypatch.setattr(bench, "_supervised_smoke", lambda: False)
    monkeypatch.setattr(sys, "argv", ["bench.py", "--serve"])
    bench.main()
    art = json.loads((tmp_path / "benchmarks" / "serving.json").read_text())
    assert art["device_unhealthy"] is True
    assert art["rc"] == 17 and art["rows"] == []


def test_bench_probe_subprocess_wedge_signature():
    """The real probe subprocess: an injected wedged-device fault at the
    bench.probe site produces exactly the rc-17 signature (without jax
    ever initializing in the child — the env short-circuit)."""
    bench = _bench()
    faults.install(faults.FaultPlan.parse("wedged-device@bench.probe=0"))
    assert bench._probe_subprocess(0, timeout_s=60) == 17


@pytest.mark.slow
def test_bench_probe_subprocess_healthy_cpu():
    """The unwedged probe end-to-end: a real subprocess materializes a
    constant on the CPU backend and exits 0."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke-probe"],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=300, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "smoke probe ok" in out.stdout
