"""Crash-restartable serving: journal, recovery parity, deadlines, overload.

The load-bearing claims (ISSUE 10 acceptance):

- **Bit-exact recovery** — an injected ``engine-crash`` mid-flight (mixed
  prompt lengths, sampled + greedy, paged AND dense layouts, plus a crash
  DURING recovery) rebuilds the engine and re-admits every in-flight
  request from the journal such that each request's full token stream
  equals the uninterrupted run's — which itself equals the solo
  ``make_cached_decoder`` stream, so a crash is invisible in the tokens.
- **Journal corners** — a truncated tail (mid-write crash) recovers the
  longest valid prefix; a request whose LAST token was journaled but whose
  ``done`` record was not re-emits identically (promoted to DONE at
  recovery, stream unchanged); an empty journal recovers to a fresh
  engine.
- **Overload control** — deadlines shed expired requests with a structured
  rejection and a full slot/block refund; queue-depth backpressure sheds
  lowest-priority-newest first; per-class token buckets police arrival
  rates; sustained backlog enters the load-degraded best-effort lockout
  with hysteresis.
- **Degraded rebuild** — past ``degrade_after`` restarts the engine is
  rebuilt in the fallback layout (speculation off, TP off, dense rows) and
  greedy streams stay bit-exact.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_cached_decoder,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.resilience.scenarios import (
    VirtualClock,
)
from simple_distributed_machine_learning_tpu.resilience.supervisor import (
    RestartBudgetExceeded,
)
from simple_distributed_machine_learning_tpu.serve import (
    OverloadPolicy,
    RequestJournal,
    ServeMetrics,
    ServeSupervisor,
    engine_factory,
)
from simple_distributed_machine_learning_tpu.serve.journal import (
    read_journal,
    recover_state,
)
from simple_distributed_machine_learning_tpu.serve.request import (
    ACTIVE,
    DONE,
    QUEUED,
    SHED,
)

CFG = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
_STAGES = None


def _model():
    global _STAGES
    if _STAGES is None:
        _STAGES = make_gpt_stages(jax.random.key(0), CFG, 2)[0]
    return _STAGES, [s.params for s in _STAGES]


def _solo(stages, params, prompt, n_new, seed, temperature=0.0, top_k=None):
    dec = make_cached_decoder(stages, CFG, len(prompt), n_new,
                              temperature=temperature, top_k=top_k)
    out = dec(params, np.asarray(prompt, np.int32)[None],
              jax.random.key(seed))
    return np.asarray(out)[0, len(prompt):]


def _prompt(n, seed):
    return np.asarray(
        jax.random.randint(jax.random.key(seed), (n,), 0, CFG.vocab),
        np.int32)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.uninstall()
    yield
    faults.uninstall()


def _supervisor(tmp_path, name="journal.jsonl", clock=None, metrics=None,
                engine_kw=None, **sup_kw):
    stages, _ = _model()
    kw = dict(engine_kw or {})
    kw.setdefault("n_slots", 2)
    if kw.get("kv_layout", "paged") == "paged":
        kw.setdefault("block_size", 4)
        kw.setdefault("prefill_chunk", 3)
    if clock is not None:
        kw["clock"] = clock
        sup_kw["clock"] = clock
    if metrics is not None:
        kw["metrics"] = metrics
        sup_kw["metrics"] = metrics
    return ServeSupervisor(engine_factory(stages, CFG, **kw),
                           str(tmp_path / name), **sup_kw)


# ---------------------------------------------------------------------------
# journal unit behavior (no model)


def test_journal_truncated_tail_recovers_longest_valid_prefix(tmp_path):
    """A mid-write crash tears at most the tail: recovery keeps every
    fully valid line, discards the torn one, and reopening truncates so
    later appends land cleanly after the valid prefix."""
    path = str(tmp_path / "j.jsonl")
    j = RequestJournal(path, sync=False)
    j.log_submit(rid=0, prompt=[1, 2, 3], max_new=4, temp=0.0, top_k=None,
                 top_p=None, eos=None, seed=0, cls=None, prio=0,
                 ttft_dl=None, dl=None, t=1.0)
    j.append({"ev": "tok", "rid": 0, "tok": 7, "kd": [1, 2], "dkd": None})
    j.close()
    with open(path, "ab") as f:           # the torn mid-write tail
        f.write(b'{"ev":"tok","rid":0,"to')
    events, valid = read_journal(path)
    assert [e["ev"] for e in events] == ["submit", "tok"]
    assert valid < os.path.getsize(path)
    # reopen-for-append truncates the torn tail, then appends cleanly
    j2 = RequestJournal(path, sync=False)
    assert os.path.getsize(path) == valid
    assert [e["ev"] for e in j2.recovered_events] == ["submit", "tok"]
    j2.log_done(rid=0, reason="length", t=2.0)
    j2.close()
    events2, _ = read_journal(path)
    assert [e["ev"] for e in events2] == ["submit", "tok", "done"]
    # a torn line mid-file (can't happen append-only, but must not parse
    # past it): everything after the first invalid line is discarded
    with open(path, "r+b") as f:
        raw = f.read()
        f.seek(0)
        f.write(raw.replace(b'"ev":"tok"', b'"ev:"tok"', 1))
    events3, _ = read_journal(path)
    assert [e["ev"] for e in events3] == ["submit"]


def test_recover_state_promotes_finished_but_unacked(tmp_path):
    """The 'last token journaled but not acked' corner, both finish kinds:
    the snapshot is DONE with the right reason and the exact journaled
    stream — recovery must NOT re-admit (and re-decode) it."""
    base = dict(prompt=[1, 2], temp=0.0, top_k=None, top_p=None, seed=0,
                cls=None, prio=0, ttft_dl=None, dl=None, t=0.0)
    j = RequestJournal(str(tmp_path / "j.jsonl"), sync=False)
    j.log_submit(rid=0, max_new=2, eos=None, **base)       # budget finish
    j.append({"ev": "tok", "rid": 0, "tok": 5, "kd": [1, 1], "dkd": None})
    j.append({"ev": "tok", "rid": 0, "tok": 6, "kd": [2, 2], "dkd": None})
    j.log_submit(rid=1, max_new=8, eos=9, **base)          # EOS finish
    j.append({"ev": "tok", "rid": 1, "tok": 9, "kd": [3, 3], "dkd": None})
    j.log_submit(rid=2, max_new=8, eos=None, **base)       # genuinely open
    j.append({"ev": "tok", "rid": 2, "tok": 4, "kd": [4, 4], "dkd": None})
    j.close()
    snap = recover_state(read_journal(str(tmp_path / "j.jsonl"))[0])
    assert snap[0].state == DONE and snap[0].finish_reason == "length"
    assert snap[0].tokens == [5, 6]
    assert snap[1].state == DONE and snap[1].finish_reason == "eos"
    assert snap[2].state == QUEUED and snap[2].tokens == [4]
    assert list(np.asarray(snap[2].key_data)) == [4, 4]


def test_empty_journal_recovers_fresh_engine(tmp_path):
    """An empty (or absent) journal is a clean cold start: no handles, a
    fresh engine, and serving proceeds normally."""
    (tmp_path / "j.jsonl").write_bytes(b"")
    sup = _supervisor(tmp_path, "j.jsonl")
    assert sup.requests == {} and not sup.busy and sup.restarts == 0
    stages, params = _model()
    h = sup.submit(_prompt(4, 1), max_new_tokens=3, seed=5)
    sup.drain()
    sup.close()
    np.testing.assert_array_equal(
        h.tokens, _solo(stages, params, h.prompt, 3, 5))


# ---------------------------------------------------------------------------
# bit-exact crash recovery


def _fixed_run(tmp_path, name, chaos, layout="paged"):
    """Mixed prompt lengths, greedy AND sampled, with queueing (2 slots,
    4 requests) — optionally under a chaos schedule.  Returns the
    supervisor, each request's final tokens in rid order, and the specs
    (for solo-decode comparison)."""
    if layout == "paged":
        kw = {"kv_layout": "paged", "block_size": 4, "prefill_chunk": 3}
    else:
        kw = {"kv_layout": "dense"}
    if chaos:
        faults.install(faults.FaultPlan.parse(chaos))
    sup = _supervisor(tmp_path, name, engine_kw=kw)
    specs = [
        dict(prompt=_prompt(5, 1), max_new_tokens=8, seed=11),
        dict(prompt=_prompt(9, 2), max_new_tokens=6, seed=12,
             temperature=0.8, top_k=5),
        dict(prompt=_prompt(3, 3), max_new_tokens=7, seed=13),
        dict(prompt=_prompt(7, 4), max_new_tokens=5, seed=14,
             temperature=1.1, top_k=4),
    ]
    handles = [sup.submit(**s) for s in specs]
    sup.drain()
    sup.close()
    faults.uninstall()
    return sup, [list(h.tokens) for h in handles], specs


def test_crash_recovery_bitexact_paged(tmp_path):
    """THE acceptance pin: an engine crash mid-flight (mixed prompt
    lengths, greedy + sampled, paged layout) recovers every in-flight
    request from the journal with its FULL token stream equal to the
    uninterrupted run's — which equals each request's solo decode."""
    stages, params = _model()
    _, base, specs = _fixed_run(tmp_path, "base.jsonl", None)
    sup, crashed, _ = _fixed_run(tmp_path, "crash.jsonl",
                                 "engine-crash@serve.tick=3")
    assert sup.restarts == 1
    assert crashed == base
    for toks, s in zip(crashed, specs):
        np.testing.assert_array_equal(
            toks, _solo(stages, params, s["prompt"], s["max_new_tokens"],
                        s["seed"], temperature=s.get("temperature", 0.0),
                        top_k=s.get("top_k")))
    # recovery metrics observable on the handles' supervisor
    assert all(r.state == DONE for r in sup.requests.values())


def test_double_crash_recovery_bitexact(tmp_path):
    """Crash DURING recovery: the second firing lands on the rebuilt
    engine's first busy tick (the plan counts call sites globally), and
    the streams still equal the uninterrupted run's."""
    _, base, _ = _fixed_run(tmp_path, "base2.jsonl", None)
    sup, crashed, _ = _fixed_run(tmp_path, "crash2.jsonl",
                                 "engine-crash@serve.tick,after=3,times=2")
    assert sup.restarts == 2
    assert crashed == base


@pytest.mark.slow
def test_crash_recovery_bitexact_dense(tmp_path):
    """Same pin on the dense slot-row layout (whole-prompt resume
    prefill)."""
    _, base, _ = _fixed_run(tmp_path, "based.jsonl", None, layout="dense")
    sup, crashed, _ = _fixed_run(tmp_path, "crashd.jsonl",
                                 "engine-crash@serve.tick=3",
                                 layout="dense")
    assert sup.restarts == 1
    assert crashed == base


def test_admit_crash_recovers_journaled_submission(tmp_path):
    """A crash INSIDE engine.submit (the serve.admit site): the submission
    was journaled first, so recovery re-admits it and the caller's handle
    — returned from the same submit() call — completes normally."""
    stages, params = _model()
    faults.install(faults.FaultPlan.parse("engine-crash@serve.admit=1"))
    sup = _supervisor(tmp_path, "admit.jsonl")
    h0 = sup.submit(_prompt(5, 1), max_new_tokens=4, seed=21)
    h1 = sup.submit(_prompt(4, 2), max_new_tokens=4, seed=22)  # crashes
    faults.uninstall()
    assert sup.restarts == 1
    assert h1.rid == 1 and h1.state == QUEUED
    sup.drain()
    sup.close()
    for h in (h0, h1):
        assert h.state == DONE
        np.testing.assert_array_equal(
            h.tokens, _solo(stages, params, h.prompt, 4, h.seed))


def test_cold_restart_resumes_from_journal_bitexact(tmp_path):
    """The process-death path: a NEW supervisor over the dead one's
    journal replays completed prefixes onto fresh handles and continues
    in-flight requests bit-exact vs the uninterrupted run."""
    clock = VirtualClock(0.001)
    sup = _supervisor(tmp_path, "cold.jsonl", clock=clock)
    h1 = sup.submit(_prompt(5, 1), max_new_tokens=8, seed=31)
    h2 = sup.submit(_prompt(7, 2), max_new_tokens=6, seed=32,
                    temperature=0.9, top_k=4)
    for _ in range(4):
        sup.step()
    mid = [list(h1.tokens), list(h2.tokens)]
    assert 0 < len(h1.tokens) < 8
    sup.close()                            # the process "dies" here
    sup2 = _supervisor(tmp_path, "cold.jsonl", clock=VirtualClock(0.001))
    g1, g2 = sup2.requests[0], sup2.requests[1]
    assert list(g1.tokens) == mid[0] and list(g2.tokens) == mid[1]
    sup2.drain()
    sup2.close()
    # uninterrupted reference run
    sup3 = _supervisor(tmp_path, "ref.jsonl", clock=VirtualClock(0.001))
    r1 = sup3.submit(_prompt(5, 1), max_new_tokens=8, seed=31)
    r2 = sup3.submit(_prompt(7, 2), max_new_tokens=6, seed=32,
                     temperature=0.9, top_k=4)
    sup3.drain()
    sup3.close()
    assert list(g1.tokens) == list(r1.tokens)
    assert list(g2.tokens) == list(r2.tokens)


def test_finished_but_unacked_request_not_redecoded(tmp_path):
    """End-to-end twin of the recover_state corner: drop the final 'done'
    record from a real run's journal (the crash-between-token-and-ack
    window); the cold supervisor marks the request DONE with the identical
    stream instead of re-admitting it."""
    sup = _supervisor(tmp_path, "ack.jsonl")
    h = sup.submit(_prompt(5, 1), max_new_tokens=4, seed=41)
    sup.drain()
    sup.close()
    want = list(h.tokens)
    path = str(tmp_path / "ack.jsonl")
    lines = open(path, "rb").read().splitlines(keepends=True)
    assert json.loads(lines[-1])["ev"] == "done"
    open(path, "wb").write(b"".join(lines[:-1]))    # ack never landed
    sup2 = _supervisor(tmp_path, "ack.jsonl")
    g = sup2.requests[h.rid]
    assert g.state == DONE and g.finish_reason == "length"
    assert list(g.tokens) == want
    assert not sup2.busy                   # nothing re-admitted
    sup2.close()


def test_degraded_rebuild_dense_and_bitexact(tmp_path):
    """Past ``degrade_after`` restarts the rebuild applies the fallback
    rule — speculation off, dense rows — and greedy streams still equal
    the full (speculative, paged) run's."""
    stages, _ = _model()
    draft_cfg = dataclasses.replace(CFG, n_layers=1)
    draft_stages = make_gpt_stages(jax.random.key(9), draft_cfg, 1)[0]

    def run(name, chaos, degrade_after=None):
        if chaos:
            faults.install(faults.FaultPlan.parse(chaos))
        sup = ServeSupervisor(
            engine_factory(stages, CFG, n_slots=2, block_size=4,
                           draft_stages=draft_stages, draft_cfg=draft_cfg,
                           spec_k=3),
            str(tmp_path / name), degrade_after=degrade_after,
            max_restarts=3)
        h1 = sup.submit(_prompt(5, 1), max_new_tokens=8, seed=51)
        h2 = sup.submit(_prompt(7, 2), max_new_tokens=6, seed=52)
        sup.drain()
        sup.close()
        faults.uninstall()
        return sup, [list(h1.tokens), list(h2.tokens)]

    _, base = run("dbase.jsonl", None)
    sup, deg = run("dcrash.jsonl", "engine-crash@serve.tick=2",
                   degrade_after=1)
    assert sup.degraded and sup.state == "degraded"
    assert sup.engine.kv_layout == "dense" and not sup.engine.speculative
    assert deg == base


def test_restart_budget_exceeded_raises(tmp_path):
    faults.install(faults.FaultPlan.parse(
        "engine-crash@serve.tick,times=0"))      # every tick crashes
    sup = _supervisor(tmp_path, "budget.jsonl", max_restarts=2)
    sup.submit(_prompt(4, 1), max_new_tokens=4, seed=61)
    with pytest.raises(RestartBudgetExceeded, match="max_restarts=2"):
        sup.drain()
    assert sup.state == "failed" and sup.restarts == 3
    sup.close()


# ---------------------------------------------------------------------------
# deadlines + overload control (virtual clock: deterministic)


def test_deadline_shed_refunds_budget_and_counts(tmp_path):
    """An expired total deadline sheds with the structured rejection, the
    slot/block budget refunds in full, and the labeled counters land in
    the summary + Prometheus exposition."""
    clock = VirtualClock(0.001)
    metrics = ServeMetrics(clock=clock)
    sup = _supervisor(tmp_path, "dl.jsonl", clock=clock, metrics=metrics,
                      engine_kw={"n_slots": 1})
    h1 = sup.submit(_prompt(5, 1), max_new_tokens=20, seed=1)  # slot hog
    h2 = sup.submit(_prompt(5, 2), max_new_tokens=6, seed=2,
                    deadline_s=0.02)       # 20 vms: expires while queued
    sup.drain()
    assert h1.state == DONE and len(h1.tokens) == 20
    assert h2.state == SHED and h2.finish_reason == "deadline"
    assert sup.pool.n_active == 0 and sup.pool.stats()["blocks_in_use"] == 0
    s = metrics.summary()
    assert s["shed_total"] == 1 and s["shed_by_reason"] == {"deadline": 1}
    assert s["restarts"] == 0 and s["journal_bytes"] > 0
    prom = metrics.registry.prometheus_text()
    assert 'serve_shed_total{reason="deadline"} 1' in prom
    assert "serve_journal_bytes" in prom
    sup.close()


def test_deadline_sheds_active_request_midflight(tmp_path):
    """A total deadline binds THROUGH decode: an active request past its
    deadline is evicted mid-stream (slot freed now, partial tokens kept on
    the handle)."""
    clock = VirtualClock(0.001)
    sup = _supervisor(tmp_path, "dla.jsonl", clock=clock,
                      engine_kw={"n_slots": 1})
    h = sup.submit(_prompt(4, 1), max_new_tokens=40, seed=3,
                   deadline_s=0.08)
    while h.state in (QUEUED, ACTIVE):
        sup.step()
    assert h.state == SHED and h.finish_reason == "deadline"
    assert 0 < len(h.tokens) < 40
    assert sup.pool.n_active == 0
    sup.close()


def test_ttft_deadline_binds_only_before_first_token(tmp_path):
    clock = VirtualClock(0.001)
    sup = _supervisor(tmp_path, "ttft.jsonl", clock=clock,
                      engine_kw={"n_slots": 1})
    # h1 decodes long; h2's TTFT deadline expires while it waits queued
    h1 = sup.submit(_prompt(4, 1), max_new_tokens=25, seed=4,
                    ttft_deadline_s=5.0)
    h2 = sup.submit(_prompt(4, 2), max_new_tokens=4, seed=5,
                    ttft_deadline_s=0.03)
    sup.drain()
    assert h1.state == DONE         # started in time: ttft deadline spent
    assert h2.state == SHED and h2.finish_reason == "deadline"
    sup.close()


def test_backpressure_sheds_lowest_priority_newest_first(tmp_path):
    clock = VirtualClock(0.001)
    sup = _supervisor(tmp_path, "bp.jsonl", clock=clock,
                      engine_kw={"n_slots": 1},
                      overload=OverloadPolicy(max_queue_depth=2))
    a = sup.submit(_prompt(4, 1), max_new_tokens=10, seed=1)
    sup.step()                                   # a boards its slot
    b = sup.submit(_prompt(4, 2), max_new_tokens=4, seed=2, priority=0)
    c = sup.submit(_prompt(4, 3), max_new_tokens=4, seed=3, priority=0)
    # queue full, equal priority: the arrival itself sheds
    d = sup.submit(_prompt(4, 4), max_new_tokens=4, seed=4, priority=0)
    assert d.state == SHED and d.finish_reason == "backpressure"
    # queue full, higher priority: the lowest-priority NEWEST victim (c)
    # sheds and the arrival boards the queue
    e = sup.submit(_prompt(4, 5), max_new_tokens=4, seed=5, priority=2)
    assert c.state == SHED and c.finish_reason == "backpressure"
    assert e.state == QUEUED and b.state == QUEUED
    sup.drain()
    assert a.state == DONE and b.state == DONE and e.state == DONE
    sup.close()


def test_class_token_bucket_polices_rate(tmp_path):
    clock = VirtualClock(0.001)
    sup = _supervisor(tmp_path, "tb.jsonl", clock=clock,
                      overload=OverloadPolicy(
                          class_rates={"batch": (1.0, 2)}))
    hs = [sup.submit(_prompt(4, i), max_new_tokens=2, seed=i, cls="batch",
                     arrival_time=0.001 * i) for i in range(4)]
    # burst 2 admits two; the near-simultaneous rest shed with reason class
    assert [h.state for h in hs] == [QUEUED, QUEUED, SHED, SHED]
    assert hs[2].finish_reason == "class"
    # the bucket refills with (virtual) time: a later arrival admits again
    late = sup.submit(_prompt(4, 9), max_new_tokens=2, seed=9, cls="batch",
                      arrival_time=5.0)
    assert late.state == QUEUED
    sup.drain()
    sup.close()


def test_backpressure_shed_does_not_debit_class_bucket(tmp_path):
    """Regression: an arrival refused for BACKPRESSURE must not charge its
    class's token bucket — the next in-rate arrival of that class would
    otherwise shed with a misattributed 'class' reason."""
    clock = VirtualClock(0.001)
    sup = _supervisor(tmp_path, "bpb.jsonl", clock=clock,
                      engine_kw={"n_slots": 1},
                      overload=OverloadPolicy(
                          max_queue_depth=1,
                          class_rates={"batch": (1.0, 1)}))
    a = sup.submit(_prompt(4, 1), max_new_tokens=12, seed=1)
    sup.step()                                   # a boards; queue empty
    b = sup.submit(_prompt(4, 2), max_new_tokens=2, seed=2, cls="batch")
    assert b.state == QUEUED                     # bucket's burst spent
    c = sup.submit(_prompt(4, 3), max_new_tokens=2, seed=3, cls="batch",
                   arrival_time=2.0)             # bucket refilled by now...
    assert c.state == SHED and c.finish_reason == "backpressure"  # queue full
    # ...and the refused arrival did NOT consume the refill: once the
    # queue has room, the next in-rate batch arrival admits
    sup.drain()
    d = sup.submit(_prompt(4, 4), max_new_tokens=2, seed=4, cls="batch",
                   arrival_time=2.1)
    assert d.state == QUEUED, (d.state, d.finish_reason)
    sup.drain()
    sup.close()


def test_load_degraded_lockout_hysteresis(tmp_path):
    """Sustained backlog locks best-effort traffic out (reason 'class')
    until the queue drains to the low watermark — and the degraded gauge
    tracks the mode."""
    clock = VirtualClock(0.001)
    metrics = ServeMetrics(clock=clock)
    sup = _supervisor(tmp_path, "deg.jsonl", clock=clock, metrics=metrics,
                      engine_kw={"n_slots": 1},
                      overload=OverloadPolicy(degrade_queue_depth=2,
                                              recover_queue_depth=0,
                                              degraded_priority_floor=0))
    a = sup.submit(_prompt(4, 1), max_new_tokens=6, seed=1)
    sup.step()
    b = sup.submit(_prompt(4, 2), max_new_tokens=2, seed=2)
    c = sup.submit(_prompt(4, 3), max_new_tokens=2, seed=3)
    # queue depth 2 >= high watermark: best-effort arrivals now refused
    d = sup.submit(_prompt(4, 4), max_new_tokens=2, seed=4, priority=0)
    assert d.state == SHED and d.finish_reason == "class"
    assert sup.load_degraded and sup.state == "degraded"
    assert metrics.summary()["degraded"] == 1
    # priority above the floor still admits while degraded... but the
    # queue is what it is — use a high-priority probe
    e = sup.submit(_prompt(4, 5), max_new_tokens=2, seed=5, priority=2)
    assert e.state == QUEUED
    sup.drain()
    # backlog drained past the low watermark: lockout lifts
    f = sup.submit(_prompt(4, 6), max_new_tokens=2, seed=6, priority=0)
    assert f.state == QUEUED and not sup.load_degraded
    assert sup.state == "running"
    sup.drain()
    sup.close()


def test_overload_policy_validation():
    with pytest.raises(ValueError, match="max_queue_depth"):
        OverloadPolicy(max_queue_depth=0)
    with pytest.raises(ValueError, match="hysteresis"):
        OverloadPolicy(degrade_queue_depth=2, recover_queue_depth=2)
    with pytest.raises(ValueError, match="token bucket"):
        OverloadPolicy(class_rates={"x": (0.0, 2)})


def test_supervisor_constructor_validation(tmp_path):
    with pytest.raises(ValueError, match="max_restarts"):
        ServeSupervisor(lambda d: None, str(tmp_path / "x.jsonl"),
                        max_restarts=-1)
    with pytest.raises(ValueError, match="degrade_after"):
        ServeSupervisor(lambda d: None, str(tmp_path / "y.jsonl"),
                        degrade_after=0)


# ---------------------------------------------------------------------------
# CLI surface


def test_serve_chaos_cli(tmp_path, capsys):
    """--serve-chaos end to end: a mid-serve engine crash restarts through
    the supervisor, every request completes, exit 0, and the restart/
    recovery counters land in the serve metrics record."""
    from simple_distributed_machine_learning_tpu.cli import main

    tele = str(tmp_path / "tele")
    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--serve-sim", "6", "--serve-rate", "100", "--serve-slots", "2",
          "--serve-max-new", "4", "--serve-block-size", "4",
          "--serve-prefill-chunk", "3",
          "--serve-chaos", "engine-crash@serve.tick=4",
          "--telemetry-dir", tele])
    out = capsys.readouterr().out
    assert "| serve: 6/6 requests completed" in out
    assert "supervisor running, 1 restart(s)" in out
    recs = [json.loads(ln) for ln in
            open(os.path.join(tele, "metrics.jsonl"))]
    r = [x for x in recs if x.get("kind") == "serve"][-1]
    assert r["restarts"] == 1 and r["recovered_requests"] > 0
    assert r["completed"] == 6
    prom = open(os.path.join(tele, "metrics.prom")).read()
    assert "serve_restarts_total 1" in prom
    assert os.path.exists(os.path.join(tele, "journal.jsonl"))


def test_serve_deadline_cli_sheds_and_exits_zero(tmp_path, capsys):
    """--serve-deadline-ms: an overloaded 1-slot run sheds expired
    requests (structured, counted) and still exits 0 — every request is
    accounted for, completed or shed."""
    from simple_distributed_machine_learning_tpu.cli import main

    tele = str(tmp_path / "tele")
    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--serve-sim", "8", "--serve-rate", "300", "--serve-slots", "1",
          "--serve-max-new", "8", "--serve-block-size", "4",
          "--serve-deadline-ms", "200", "--telemetry-dir", tele])
    out = capsys.readouterr().out
    recs = [json.loads(ln) for ln in
            open(os.path.join(tele, "metrics.jsonl"))]
    r = [x for x in recs if x.get("kind") == "serve"][-1]
    assert r["shed_total"] > 0
    assert r["completed"] + r["shed_total"] == 8
    assert "shed {'deadline':" in out


def test_serve_supervisor_cli_flag_validation():
    from simple_distributed_machine_learning_tpu.cli import main

    base = ["--rank", "0", "--world_size", "1", "--model", "gpt",
            "--serve-sim", "2"]
    with pytest.raises(SystemExit, match="serve-deadline-ms"):
        main(base + ["--serve-deadline-ms", "-5"])
    with pytest.raises(SystemExit, match="serve-max-restarts"):
        main(base + ["--serve-max-restarts", "-1"])
    with pytest.raises(SystemExit, match="bad --serve-chaos"):
        main(base + ["--serve-chaos", "nonsense"])
    with pytest.raises(SystemExit, match="bad --serve-chaos"):
        # a typo'd site must refuse, not pass vacuously
        main(base + ["--serve-chaos", "engine-crash@serve.tock=3"])


@pytest.mark.slow
def test_sigterm_graceful_shutdown_subprocess(tmp_path):
    """SIGTERM mid-serve: admission stops, in-flight requests drain,
    metrics + journal flush, exit 0 — the operational complement of crash
    recovery (a rollout must not look like a fault)."""
    tele = str(tmp_path / "tele")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "simple_distributed_machine_learning_tpu.cli", "--rank", "0",
         "--world_size", "1", "--model", "gpt", "--serve-sim", "500",
         "--serve-rate", "2", "--serve-slots", "2", "--serve-max-new", "4",
         "--serve-block-size", "4", "--serve-deadline-ms", "60000",
         "--telemetry-dir", tele],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)))
    try:
        # wait until serving is actually under way (params line printed),
        # then give the engine a moment to be mid-trace before the signal
        deadline = time.time() + 180
        while time.time() < deadline:
            line = proc.stdout.readline()
            if "serve: supervised" in line:
                break
        else:
            raise AssertionError("serving never started")
        time.sleep(10)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == 0, out
    assert "graceful shutdown on signal" in out
    assert "admission stopped" in out
    # metrics + journal were flushed on the way out
    recs = [json.loads(ln) for ln in
            open(os.path.join(tele, "metrics.jsonl"))]
    assert any(r.get("kind") == "serve" for r in recs)
    events, _ = read_journal(os.path.join(tele, "journal.jsonl"))
    assert any(e["ev"] == "submit" for e in events)


# ---------------------------------------------------------------------------
# bench availability


def test_bench_availability_under_crash():
    """The bench availability row: with a generous deadline, an injected
    mid-flight crash costs a restart, never a completion — availability
    pins at 1.0 with >= 1 restart and recovered requests > 0."""
    import jax as _jax

    from bench import _measure_availability
    from simple_distributed_machine_learning_tpu.models.gpt import (
        make_gpt_stages as _mk,
    )

    stages = _mk(_jax.random.key(0), CFG, n_stages=1)[0]
    [row] = _measure_availability(stages, CFG, slots=3, n_requests=8,
                                  max_new=6, prompt_lens=(4, 8),
                                  block_size=4)
    assert row["availability"] == 1.0
    assert row["completed"] == 8 and row["shed_deadline"] == 0
    assert row["restarts"] >= 1 and row["faults_fired"] == 1
    assert row["recovered_requests"] > 0
