"""Sequence-parallel GPT end-to-end: T sharded over the mesh's seq axis.

VERDICT r1 item 5: ring/Ulysses attention must be reachable from the model,
not just as library functions. These tests run the FULL pipeline engine (2
stages x 2 seq shards = 4 devices) with the token axis sharded end to end —
seq-chunked wire, position-offset embeddings, collective attention, seq-psum'd
loss — and assert exact agreement with the dense single-sequence pipeline.
"""

import dataclasses

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.parallel.compat import HAS_VMA

# ring attention's ppermutes sit inside the engine's per-stage lax.switch
# branches; old jax's XLA:CPU collective-permute rendezvous is global across
# devices, so branch-divergent rings deadlock there instead of failing (on
# TPU, and on modern jax's partitioned lowering, the permutes are
# independent). Skip rather than hang the suite.
ring_in_pipeline = pytest.param("ring", marks=pytest.mark.skipif(
    not HAS_VMA, reason="branch-divergent ppermute rings deadlock on old "
                        "jax's XLA:CPU collective-permute rendezvous"))
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import make_train_step

CFG = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=4, n_layers=2)


def _data(key, batch):
    kx, ky = jax.random.split(key)
    x = jax.random.randint(kx, (batch, CFG.seq_len), 0, CFG.vocab)
    y = jax.random.randint(ky, (batch, CFG.seq_len), 0, CFG.vocab)
    return x.astype(jax.numpy.float32), y


def _dense_pipe(n_micro=2):
    stages, wd, od = make_gpt_stages(jax.random.key(0), CFG, 2)
    mesh = make_mesh(n_stages=2, n_data=1, n_seq=1)
    return Pipeline(stages, mesh, wd, od, n_microbatches=n_micro)


def _sp_pipe(attn, n_micro=2):
    cfg = dataclasses.replace(CFG, attn_impl=attn, n_seq=2)
    stages, wd, od = make_gpt_stages(jax.random.key(0), cfg, 2)
    mesh = make_mesh(n_stages=2, n_data=1, n_seq=2)
    return Pipeline(stages, mesh, wd, od, n_microbatches=n_micro)


@pytest.mark.parametrize("attn", [ring_in_pipeline, "ulysses"])
def test_gpt_sp_loss_and_logits_match_dense(attn):
    x, y = _data(jax.random.key(1), 4)
    key = jax.random.key(2)

    dense = _dense_pipe()
    ld, logits_d = dense.loss_and_logits(dense.init_params(), x, y, key,
                                         deterministic=True)
    sp = _sp_pipe(attn)
    ls, logits_s = sp.loss_and_logits(sp.init_params(), x, y, key,
                                      deterministic=True)
    np.testing.assert_allclose(float(ls), float(ld), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(logits_s), np.asarray(logits_d),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("attn", [ring_in_pipeline, "ulysses"])
def test_gpt_sp_sgd_trajectory_matches_dense(attn):
    """Two SGD(momentum) steps: the seq-sharded engine's gradients (through
    ppermute stage hops AND the attention collective) must reproduce the
    dense pipeline's trajectory."""
    x, y = _data(jax.random.key(3), 4)
    opt = sgd(0.1, momentum=0.5)

    losses = {}
    for name, pipe in (("dense", _dense_pipe()), (attn, _sp_pipe(attn))):
        buf = pipe.init_params()
        state = opt.init(buf)
        step = make_train_step(pipe, opt)
        ls = []
        for i in range(2):
            buf, state, loss = step(buf, state, x, y,
                                    jax.random.fold_in(jax.random.key(4), i))
            ls.append(float(loss))
        losses[name] = ls
    np.testing.assert_allclose(losses[attn], losses["dense"],
                               rtol=5e-5, atol=5e-5)


def test_gpt_sp_trainer_epoch_runs():
    """The Trainer drives a seq-sharded GPT end to end (VERDICT r1 item 5)."""
    from simple_distributed_machine_learning_tpu.data.mnist import Dataset
    from simple_distributed_machine_learning_tpu.train.trainer import (
        TrainConfig,
        Trainer,
    )

    x, y = _data(jax.random.key(5), 8)
    ds = Dataset(np.asarray(x), np.asarray(y))
    pipe = _sp_pipe("ulysses")
    tr = Trainer(pipe, ds, ds,
                 TrainConfig(epochs=1, batch_size=4, print_throughput=False))
    loss = tr.train_epoch(1)
    assert np.isfinite(loss)
    avg, correct = tr.evaluate()
    assert np.isfinite(avg) and 0 <= correct <= y.size


def test_gpt_config_rejects_bad_sp():
    with pytest.raises(ValueError, match="divisible"):
        GPTConfig(n_seq=3, seq_len=16, attn_impl="ring")
    with pytest.raises(ValueError, match="sequence-parallel attention"):
        GPTConfig(n_seq=2, attn_impl="dense")
    with pytest.raises(ValueError, match="n_heads"):
        GPTConfig(n_seq=4, n_heads=6, attn_impl="ulysses")
