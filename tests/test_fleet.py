"""Multi-replica fleet: router, cross-replica migration, autoscaler, journal
rotation.

The load-bearing claims (ISSUE 13 acceptance):

- **Bit-exact cross-replica migration** — killing a whole replica
  mid-decode (``replica-kill@fleet.tick``) re-admits its in-flight
  requests onto the survivors from its journal ALONE, and every migrated
  request's full token stream equals the uninterrupted run's — which
  equals the solo ``make_cached_decoder`` stream — across a double
  replica loss and a loss landing during another replica's crash
  recovery. The adopting replica's journal is self-contained: crashing
  the ADOPTER after a migration still recovers the adoptee bit-exact.
- **Routing** — affinity routes to the replica whose paged pool already
  holds the prompt's registered prefix (hot-prefix-skew pins affinity's
  prefix-hit counters STRICTLY above round-robin's on exact numbers);
  rids are fleet-unique; unhealthy replicas drain out of rotation and
  re-enter with hysteresis.
- **Autoscaler** — the diurnal scenario's exact virtual-clock trajectory:
  scale-out ticks at the first peak, drain-then-retire ticks in the
  trough, scale-out again at the second peak.
- **Journal rotation** (satellite) — ``RequestJournal.rotate()`` compacts
  to per-request ``snap`` records; recovery after rotation is
  byte-identical to recovery from the unrotated journal.
- **No mutable-default aliasing** (satellite) — one ``OverloadPolicy``
  shared by N replicas keeps PER-REPLICA token-bucket fills: one
  replica's debit never appears in another's.
- **SHED stays shed** (satellite) — ``recover_state`` over a journal with
  shed/cancelled records interleaved with restarts never re-admits a
  shed request.
"""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_cached_decoder,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.resilience.scenarios import (
    SCENARIOS,
    VirtualClock,
    run_scenario,
)
from simple_distributed_machine_learning_tpu.serve import (
    AutoscalePolicy,
    FleetRouter,
    OverloadPolicy,
    RequestJournal,
    ServeFleet,
    ServeSupervisor,
    engine_factory,
)
from simple_distributed_machine_learning_tpu.serve.journal import (
    read_journal,
    recover_state,
)
from simple_distributed_machine_learning_tpu.serve.request import (
    DONE,
    QUEUED,
    SHED,
)

CFG = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
_STAGES = None


def _model():
    global _STAGES
    if _STAGES is None:
        _STAGES = make_gpt_stages(jax.random.key(0), CFG, 2)[0]
    return _STAGES, [s.params for s in _STAGES]


def _solo(stages, params, prompt, n_new, seed, temperature=0.0, top_k=None):
    dec = make_cached_decoder(stages, CFG, len(prompt), n_new,
                              temperature=temperature, top_k=top_k)
    out = dec(params, np.asarray(prompt, np.int32)[None],
              jax.random.key(seed))
    return np.asarray(out)[0, len(prompt):]


def _prompt(n, seed):
    return np.asarray(
        jax.random.randint(jax.random.key(seed), (n,), 0, CFG.vocab),
        np.int32)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.uninstall()
    yield
    faults.uninstall()


def _fleet(tmp_path, name, clock=None, metrics=None, n_replicas=3,
           engine_kw=None, **fleet_kw):
    stages, _ = _model()
    kw = dict(engine_kw or {})
    kw.setdefault("n_slots", 2)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 3)
    if clock is not None:
        kw["clock"] = clock
        fleet_kw["clock"] = clock
    if metrics is not None:
        kw["metrics"] = metrics
        fleet_kw["metrics"] = metrics
    return ServeFleet(engine_factory(stages, CFG, **kw),
                      os.path.join(str(tmp_path), name),
                      n_replicas=n_replicas, journal_sync=False,
                      **fleet_kw)


_SPECS = [
    dict(prompt_seed=1, prompt_len=5, max_new_tokens=8, seed=11),
    dict(prompt_seed=2, prompt_len=9, max_new_tokens=6, seed=12,
         temperature=0.8, top_k=5),
    dict(prompt_seed=3, prompt_len=3, max_new_tokens=7, seed=13),
    dict(prompt_seed=4, prompt_len=7, max_new_tokens=5, seed=14,
         temperature=1.1, top_k=4),
]


def _fixed_run(tmp_path, name, chaos, **fleet_kw):
    """The mixed workload (greedy + sampled, varied prompt lengths) over a
    3-replica fleet — optionally under a chaos schedule. Returns the
    fleet and each request's final tokens in rid order."""
    if chaos:
        faults.install(faults.FaultPlan.parse(chaos))
    fleet = _fleet(tmp_path, name, **fleet_kw)
    handles = []
    for s in _SPECS:
        s = dict(s)
        prompt = _prompt(s.pop("prompt_len"), s.pop("prompt_seed"))
        handles.append(fleet.submit(prompt, **s))
    fleet.drain()
    fleet.close()
    faults.uninstall()
    return fleet, [list(h.tokens) for h in handles]


# ---------------------------------------------------------------------------
# bit-exact cross-replica migration


def test_replica_loss_migrates_bitexact():
    """THE acceptance pin: a whole replica killed mid-decode migrates its
    in-flight requests onto the survivors from its journal alone, and
    every stream equals the uninterrupted fleet run's — which equals each
    request's solo decode."""
    import tempfile

    stages, params = _model()
    base_dir = tempfile.TemporaryDirectory()
    kill_dir = tempfile.TemporaryDirectory()
    _, base = _fixed_run(base_dir.name, "b", None)
    fleet, killed = _fixed_run(kill_dir.name, "k",
                               "replica-kill@fleet.tick=3")
    assert fleet.replica_losses == 1 and fleet.migrations >= 1
    assert killed == base
    for toks, s in zip(killed, _SPECS):
        np.testing.assert_array_equal(
            toks, _solo(stages, params,
                        _prompt(s["prompt_len"], s["prompt_seed"]),
                        s["max_new_tokens"], s["seed"],
                        temperature=s.get("temperature", 0.0),
                        top_k=s.get("top_k")))
    assert all(r.state == DONE for r in fleet.requests.values())
    base_dir.cleanup()
    kill_dir.cleanup()


def test_double_replica_loss_bitexact(tmp_path):
    """Two replicas die at the same fleet tick: the first loss migrates
    onto a replica the second loss then kills — the adoptee recovers AGAIN
    from the adopter's journal (the snap record makes it self-contained)
    and the streams still match the uninterrupted run."""
    _, base = _fixed_run(tmp_path / "base", "b", None)
    fleet, killed = _fixed_run(tmp_path / "kill", "k",
                               "replica-kill@fleet.tick=3,times=2")
    assert fleet.replica_losses == 2
    assert fleet.migrations >= 2
    assert killed == base


def test_replica_loss_during_another_replicas_recovery(tmp_path):
    """An engine-crash puts one replica into its post-recovery re-prefill
    (out of rotation, restart consumed); a replica-kill lands on ANOTHER
    replica one tick later — migration routes around the recovering
    replica and every stream stays bit-exact."""
    _, base = _fixed_run(tmp_path / "base", "b", None)
    fleet, crashed = _fixed_run(
        tmp_path / "kill", "k",
        "engine-crash@serve.tick=3;replica-kill@fleet.tick=4,rank=1")
    assert fleet.replica_losses == 1
    assert sum(r.supervisor.restarts for r in fleet.replicas) == 1
    assert crashed == base


def test_adopter_crash_after_migration_bitexact(tmp_path):
    """The adopting replica's journal is self-contained: crash the
    ADOPTER's engine after it adopted migrated work — supervisor-level
    journal recovery replays the snap record plus the tokens appended
    after it, and the streams still equal the uninterrupted run's."""
    _, base = _fixed_run(tmp_path / "base", "b", None)
    fleet, crashed = _fixed_run(
        tmp_path / "kill", "k",
        "replica-kill@fleet.tick=3;engine-crash@serve.tick,after=8")
    assert fleet.replica_losses == 1
    assert sum(r.supervisor.restarts for r in fleet.replicas) >= 1
    assert crashed == base


def test_fleet_rids_are_globally_unique(tmp_path):
    """The fleet owns the rid space: requests routed to different
    replicas never collide on a rid (journals, traces and metrics join on
    it)."""
    fleet = _fleet(tmp_path, "rids", n_replicas=3,
                   route="round-robin")
    hs = [fleet.submit(_prompt(4, i), max_new_tokens=2, seed=i)
          for i in range(6)]
    assert [h.rid for h in hs] == list(range(6))
    homes = {fleet._home[h.rid] for h in hs}
    assert len(homes) == 3          # round-robin actually spread the load
    fleet.drain()
    fleet.close()
    assert all(h.state == DONE for h in hs)


# ---------------------------------------------------------------------------
# health-aware rotation


def test_crash_recovered_replica_reenters_with_hysteresis(tmp_path):
    """A replica that consumed a restart drains out of rotation the same
    tick and re-enters only after ``health_recover_ticks`` consecutive
    healthy ticks — the drain/re-enter transitions land in the
    replica_log."""
    faults.install(faults.FaultPlan.parse("engine-crash@serve.tick=2"))
    fleet = _fleet(tmp_path, "hyst", n_replicas=2,
                   health_recover_ticks=3)
    for s in _SPECS:
        s = dict(s)
        fleet.submit(_prompt(s.pop("prompt_len"), s.pop("prompt_seed")),
                     **s)
    fleet.drain()
    fleet.close()
    faults.uninstall()
    events = [(e["event"], e["replica"]) for e in fleet.replica_log]
    assert ("drain", 0) in events and ("re-enter", 0) in events
    drain_t = next(e["tick"] for e in fleet.replica_log
                   if e["event"] == "drain")
    reenter_t = next(e["tick"] for e in fleet.replica_log
                     if e["event"] == "re-enter")
    assert reenter_t - drain_t >= 3          # the hysteresis actually held
    assert all(r.state == DONE for r in fleet.requests.values())


def test_restart_budget_exhaustion_is_a_replica_loss(tmp_path):
    """A replica whose supervisor exhausts its restart budget is a LOST
    replica, not a fleet crash: its in-flight work migrates and the run
    completes."""
    # every tick of replica 0's engine crashes; with max_restarts=1 the
    # second crash exhausts its budget and the fleet absorbs the loss
    faults.install(faults.FaultPlan.parse(
        "engine-crash@serve.tick,times=2"))
    fleet = _fleet(tmp_path, "budget", n_replicas=2, max_restarts=1)
    h = fleet.submit(_prompt(5, 1), max_new_tokens=4, seed=21)
    fleet.drain()
    fleet.close()
    faults.uninstall()
    assert fleet.replica_losses == 1 and fleet.n_alive == 1
    assert h.state == DONE
    stages, params = _model()
    np.testing.assert_array_equal(
        h.tokens, _solo(stages, params, h.prompt, 4, 21))


# ---------------------------------------------------------------------------
# routing: hot-prefix skew (exact pins)


def test_hot_prefix_affinity_beats_round_robin_pinned():
    """The hot-prefix-skew scenario on both routing policies: affinity
    concentrates the shared prefix on one replica (17 prefix-share hits —
    every request after the first) while round-robin re-prefills it on
    every replica (5 hits) — strictly above, on exact pinned numbers."""
    stages, _ = _model()
    aff = run_scenario("hot-prefix-skew", stages, CFG)
    rr = run_scenario("hot-prefix-skew", stages, CFG, route="round-robin")
    assert aff["slo_ok"] is True and rr["completed"] == 18
    assert aff["prefix_hit_blocks"] == 17
    assert rr["prefix_hit_blocks"] == 5
    assert aff["prefix_hit_blocks"] > rr["prefix_hit_blocks"]
    assert aff["fleet"]["affinity_hits"] == 17
    assert rr["fleet"]["affinity_hits"] == 0


def test_affinity_routes_to_prefix_holder(tmp_path):
    """Unit form of the affinity signal: once a replica registered a
    prompt's blocks, a request sharing that prefix routes to THAT replica
    even when another is less loaded."""
    clock = VirtualClock(0.001)
    fleet = _fleet(tmp_path, "aff", clock=clock, n_replicas=2,
                   engine_kw={"n_slots": 2, "block_size": 4,
                              "prefill_chunk": None})
    p = _prompt(8, 7)
    h0 = fleet.submit(p, max_new_tokens=2, seed=1)
    fleet.drain()                     # registers p's blocks on h0's home
    h1 = fleet.submit(np.concatenate([p, _prompt(3, 8)]),
                      max_new_tokens=2, seed=2)
    assert fleet._home[h1.rid] == fleet._home[h0.rid]
    fleet.drain()
    fleet.close()


# ---------------------------------------------------------------------------
# autoscaler: the diurnal trajectory (exact pins)


def test_diurnal_autoscale_trajectory_pinned():
    """The fleet-autoscale-diurnal scenario walks the whole autoscaler
    state machine in one virtual-clock run, and the trajectory is EXACT:
    scale-out to 3 at the first peak (ticks 30/36), drain-then-retire
    back to 1 in the trough (tick 61), scale-out again at the second
    peak (ticks 76/78)."""
    stages, _ = _model()
    report = run_scenario("fleet-autoscale-diurnal", stages, CFG)
    assert report["slo_ok"] is True
    assert report["completed"] == 50
    log = [(e["event"], e["replica"], e["tick"], e["alive"])
           for e in report["fleet"]["replica_log"]]
    assert log == [
        ("scale-out", 1, 30, 2),
        ("scale-out", 2, 36, 3),
        ("retire", 2, 61, 2),
        ("retire", 1, 61, 1),
        ("scale-out", 3, 76, 2),
        ("scale-out", 4, 78, 3),
    ]
    assert report["fleet"]["scale_outs"] == 4
    assert report["fleet"]["retired"] == 2


def test_budget_exhaustion_during_admission_is_a_replica_loss(tmp_path):
    """An admission crash (serve.admit) on a replica whose restart budget
    is already spent must lose THAT replica and migrate the journaled
    submission onto a survivor — never crash the whole fleet out of
    submit()."""
    stages, params = _model()
    faults.install(faults.FaultPlan.parse("engine-crash@serve.admit=1"))
    fleet = _fleet(tmp_path, "admitloss", n_replicas=2, max_restarts=0)
    h0 = fleet.submit(_prompt(5, 1), max_new_tokens=4, seed=21)
    h1 = fleet.submit(_prompt(4, 2), max_new_tokens=4, seed=22)  # crashes
    faults.uninstall()
    assert fleet.replica_losses == 1 and fleet.n_alive == 1
    assert h1.rid == 1 and h1.state == QUEUED
    fleet.drain()
    fleet.close()
    for h in (h0, h1):
        assert h.state == DONE
        np.testing.assert_array_equal(
            h.tokens, _solo(stages, params, h.prompt, 4, h.seed))


def test_wall_clock_idle_retire_anchored_at_observation(tmp_path):
    """Regression: on a wall-style clock (absolute monotonic values, not
    a virtual clock starting at 0) the autoscaler must NOT retire the
    initial replicas the moment it learns the first real timestamp —
    idleness is anchored at the first idle OBSERVATION, so the clock base
    cancels out."""
    class OffsetClock(VirtualClock):
        def __init__(self):
            super().__init__(0.001)
            self._t = 50_000.0               # monotonic-style absolute base

    clock = OffsetClock()
    fleet = _fleet(tmp_path, "wall", clock=clock, n_replicas=2,
                   autoscale=AutoscalePolicy(min_replicas=1,
                                             max_replicas=2,
                                             retire_idle_s=0.5))
    h = fleet.submit(_prompt(4, 1), max_new_tokens=2, seed=1,
                     arrival_time=50_000.5)
    # the huge absolute timestamp must not read as 50k seconds of idleness
    assert fleet.n_alive == 2
    fleet.drain()
    assert h.state == DONE
    # genuine idleness still retires: observe idle, then advance past the
    # threshold via a later arrival
    h2 = fleet.submit(_prompt(4, 2), max_new_tokens=2, seed=2,
                      arrival_time=50_010.0)
    assert fleet.n_alive == 1
    fleet.drain()
    fleet.close()
    assert h2.state == DONE


def test_autoscale_floor_replaces_lost_replica(tmp_path):
    """An autoscaled fleet losing a replica below min_replicas replaces
    it on the next tick — the floor binds on the loss side, not only
    against retirement."""
    faults.install(faults.FaultPlan.parse("replica-kill@fleet.tick=2"))
    fleet = _fleet(tmp_path, "floor", n_replicas=2,
                   autoscale=AutoscalePolicy(min_replicas=2,
                                             max_replicas=3))
    h = fleet.submit(_prompt(5, 1), max_new_tokens=4, seed=1)
    fleet.drain()
    fleet.close()
    faults.uninstall()
    assert fleet.replica_losses == 1 and fleet.n_alive == 2
    assert [(e["event"], e["replica"]) for e in fleet.replica_log] == \
        [("loss", 0), ("replace", 2)]
    assert h.state == DONE


def test_fleet_replica_restart_writes_tagged_postmortem(tmp_path):
    """An in-place replica restart under a fleet keeps the PR-11 crash
    forensics: the bundle lands in the shared dir with the replica tag in
    its name, so N replicas never overwrite each other's bundles."""
    faults.install(faults.FaultPlan.parse("engine-crash@serve.tick=2"))
    fleet = _fleet(tmp_path, "pm", n_replicas=2,
                   postmortem_dir=str(tmp_path))
    h = fleet.submit(_prompt(5, 1), max_new_tokens=4, seed=1)
    fleet.drain()
    fleet.close()
    faults.uninstall()
    assert h.state == DONE
    bundles = sorted(p.name for p in tmp_path.glob("postmortem-*.json"))
    assert bundles and all("-r" in b for b in bundles), bundles


# ---------------------------------------------------------------------------
# the fleet-replica-loss scenario gate


def test_fleet_replica_loss_scenario_gate():
    """The catalog entry: all requests complete through the loss, at
    least one migration actually happened, SLOs held."""
    stages, _ = _model()
    report = run_scenario("fleet-replica-loss", stages, CFG)
    assert report["slo_ok"] is True
    assert report["completed"] == 16
    assert report["fleet"]["replica_losses"] == 1
    assert report["fleet"]["migrations"] >= 1


def test_fleet_scenario_gate_requires_migrations():
    """The vacuous-pass guard: the same scenario with its fault stripped
    must FAIL the gate (min_migrations unmet), not pass because nothing
    went wrong."""
    stages, _ = _model()
    quiet = dataclasses.replace(SCENARIOS["fleet-replica-loss"],
                                name="fleet-no-kill", chaos=None)
    report = run_scenario(quiet, stages, CFG)
    assert report["completed"] == 16          # nothing wrong with the run
    assert report["fleet"]["migrations"] == 0
    assert report["slo_ok"] is False          # the gate caught the silence


def test_fleet_scenario_emits_gateable_record(tmp_path):
    """With an outdir, the scenario lands its fleet block in the
    metrics.jsonl record CI re-asserts from, and the per-replica journals
    sit next to it."""
    stages, _ = _model()
    report = run_scenario("fleet-replica-loss", stages, CFG,
                          outdir=str(tmp_path))
    assert report["slo_ok"] is True
    recs = [json.loads(ln) for ln in open(tmp_path / "metrics.jsonl")]
    scen = [r for r in recs if r.get("kind") == "scenario"][-1]
    assert scen["fleet"]["migrations"] >= 1
    assert scen["fleet"]["replica_losses"] == 1
    serve = [r for r in recs if r.get("kind") == "serve"][-1]
    assert serve["fleet_migrations"] == scen["fleet"]["migrations"]
    journals = sorted(p.name for p in tmp_path.glob(
        "journal-fleet-replica-loss-r*.jsonl"))
    assert len(journals) == 3
    prom = open(tmp_path / "metrics.prom").read()
    for name in ("serve_fleet_replicas", "serve_fleet_migrations_total",
                 "serve_route_affinity_hits_total"):
        assert f"# HELP {name}" in prom, name


# ---------------------------------------------------------------------------
# journal rotation (satellite)


def test_journal_rotation_recovery_byte_identical(tmp_path):
    """The satellite pin: rotate() compacts a real run's journal to snap
    records, reclaims bytes, and recovery from the rotated journal is
    byte-identical to recovery from the unrotated one."""
    stages, _ = _model()
    path = str(tmp_path / "rot.jsonl")
    sup = ServeSupervisor(
        engine_factory(stages, CFG, n_slots=2, block_size=4,
                       prefill_chunk=3),
        RequestJournal(path, sync=False))
    h1 = sup.submit(_prompt(5, 1), max_new_tokens=8, seed=31)
    h2 = sup.submit(_prompt(7, 2), max_new_tokens=6, seed=32,
                    temperature=0.9, top_k=4)
    for _ in range(6):
        sup.step()
    assert 0 < len(h1.tokens) < 8            # genuinely mid-flight

    def snap_key(snaps):
        return {rid: (r.state, r.finish_reason, list(r.tokens),
                      None if r.key_data is None
                      else [int(x) for x in np.asarray(r.key_data)],
                      None if r.draft_key_data is None
                      else [int(x) for x in np.asarray(r.draft_key_data)],
                      r.submit_time, r.first_token_time, r.done_time,
                      [int(x) for x in np.asarray(r.prompt)],
                      r.max_new_tokens, r.seed, r.temperature, r.top_k)
                for rid, r in snaps.items()}

    before = snap_key(sup.journal.recovered_state())
    pre_bytes = sup.journal.bytes
    reclaimed = sup.journal.rotate()
    assert reclaimed > 0 and sup.journal.bytes < pre_bytes
    assert snap_key(sup.journal.recovered_state()) == before
    # the live supervisor keeps appending cleanly after the rotation, and
    # a cold restart over the rotated journal continues bit-exact
    sup.drain()
    sup.close()
    done = [list(h1.tokens), list(h2.tokens)]
    sup2 = ServeSupervisor(
        engine_factory(stages, CFG, n_slots=2, block_size=4,
                       prefill_chunk=3),
        RequestJournal(path, sync=False))
    assert not sup2.busy                     # everything recovered DONE
    assert [list(sup2.requests[h1.rid].tokens),
            list(sup2.requests[h2.rid].tokens)] == done
    sup2.close()


def test_journal_rotation_shrinks_long_history(tmp_path):
    """The motivating case: a long token history compacts to one snap
    line per request — the cold-restart replay stops re-reading every
    token record."""
    stages, _ = _model()
    path = str(tmp_path / "long.jsonl")
    sup = ServeSupervisor(
        engine_factory(stages, CFG, n_slots=2, block_size=4,
                       prefill_chunk=3),
        RequestJournal(path, sync=False))
    for i in range(4):
        sup.submit(_prompt(4, i), max_new_tokens=16, seed=40 + i)
    sup.drain()
    n_events_before = len(read_journal(path)[0])
    reclaimed = sup.journal.rotate()
    events_after = read_journal(path)[0]
    assert reclaimed > 0
    assert len(events_after) == 4            # one snap per request
    assert {e["ev"] for e in events_after} == {"snap"}
    assert n_events_before > 4 * 16          # it really was a long history
    sup.close()


# ---------------------------------------------------------------------------
# overload-policy aliasing (satellite bugfix pin)


def test_token_bucket_not_shared_across_replicas(tmp_path):
    """ONE OverloadPolicy instance shared by a two-replica fleet: replica
    A's token-bucket debit must not appear in replica B's. Round-robin
    routing pins which replica each submission lands on."""
    clock = VirtualClock(0.001)
    policy = OverloadPolicy(class_rates={"batch": (0.1, 1)})
    fleet = _fleet(tmp_path, "buckets", clock=clock, n_replicas=2,
                   route="round-robin", overload=policy)
    a = fleet.submit(_prompt(4, 1), max_new_tokens=2, seed=1, cls="batch",
                     arrival_time=0.001)
    b = fleet.submit(_prompt(4, 2), max_new_tokens=2, seed=2, cls="batch",
                     arrival_time=0.002)
    assert fleet._home[a.rid] != fleet._home[b.rid]
    # A's burst-1 bucket is spent on a; b landed on B's OWN full bucket
    assert a.state == QUEUED and b.state == QUEUED
    # a third arrival cycles back to replica A, whose bucket IS spent
    c = fleet.submit(_prompt(4, 3), max_new_tokens=2, seed=3, cls="batch",
                     arrival_time=0.003)
    assert c.state == SHED and c.finish_reason == "class"
    fleet.drain()
    fleet.close()


def test_overload_policy_class_rates_defensively_copied():
    """The aliasing fix itself: the policy snapshots class_rates at
    construction — mutating the caller's dict afterwards cannot retune
    (or couple) the replicas that share the policy."""
    rates = {"batch": (1.0, 2)}
    policy = OverloadPolicy(class_rates=rates)
    rates["batch"] = (1000.0, 99)
    rates["new"] = (1.0, 1)
    assert policy.class_rates == {"batch": (1.0, 2.0)}


# ---------------------------------------------------------------------------
# recover_state: shed/cancelled interleaved with restarts (satellite)


def test_recover_state_shed_and_cancelled_stay_shed(tmp_path):
    """The fleet re-admit path feeds recover_state journals with SHED and
    cancelled records interleaved with restarts — shed requests must stay
    shed, never re-admitted."""
    path = str(tmp_path / "shed.jsonl")
    j = RequestJournal(path, sync=False)
    base = dict(temp=0.0, top_k=None, top_p=None, seed=0, cls=None,
                prio=0, ttft_dl=None, dl=None)
    j.log_submit(rid=0, prompt=[1, 2], max_new=8, eos=None, t=1.0, **base)
    j.append({"ev": "tok", "rid": 0, "tok": 5, "kd": [1, 1], "dkd": None})
    j.log_shed(rid=0, reason="deadline", t=1.5, tick=2)
    j.log_restart(1, False, "EngineCrash", tick=3)
    j.log_submit(rid=1, prompt=[3, 4], max_new=4, eos=None, t=2.0, **base)
    j.log_shed(rid=1, reason="cancelled", t=2.2, tick=4)
    j.log_submit(rid=2, prompt=[5, 6], max_new=4, eos=None, t=2.5, **base)
    j.append({"ev": "tok", "rid": 2, "tok": 7, "kd": [2, 2], "dkd": None})
    j.log_restart(2, False, "ReplicaLost", tick=5)
    j.log_submit(rid=3, prompt=[7], max_new=2, eos=None, t=3.0, **base)
    j.log_shed(rid=3, reason="backpressure", t=3.1, tick=6)
    j.close()
    snap = recover_state(read_journal(path)[0])
    assert snap[0].state == SHED and snap[0].finish_reason == "deadline"
    assert snap[0].tokens == [5]             # partial stream kept readable
    assert snap[1].state == SHED and snap[1].finish_reason == "cancelled"
    assert snap[3].state == SHED
    assert snap[2].state == QUEUED and snap[2].tokens == [7]
    # end to end: a supervisor over this journal re-admits ONLY rid 2
    stages, _ = _model()
    sup = ServeSupervisor(
        engine_factory(stages, CFG, n_slots=2, block_size=4,
                       prefill_chunk=3),
        RequestJournal(path, sync=False))
    assert sorted(sup._open) == [2]
    assert sup.requests[0].state == SHED
    assert sup.requests[1].state == SHED
    assert sup.requests[3].state == SHED
    sup.drain()
    assert sup.requests[2].state == DONE
    assert sup.requests[0].state == SHED     # still shed after the drain
    sup.close()


# ---------------------------------------------------------------------------
# validation + fault plumbing


def test_router_and_autoscale_validation():
    with pytest.raises(ValueError, match="route policy"):
        FleetRouter("fastest")
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscalePolicy(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscalePolicy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="retire_idle_s"):
        AutoscalePolicy(retire_idle_s=0)
    with pytest.raises(ValueError, match="kv_frac_high"):
        AutoscalePolicy(kv_frac_high=1.5)


def test_fleet_constructor_validation(tmp_path):
    with pytest.raises(ValueError, match="n_replicas"):
        _fleet(tmp_path, "v1", n_replicas=0)
    with pytest.raises(ValueError, match="autoscale bounds"):
        _fleet(tmp_path, "v2", n_replicas=5,
               autoscale=AutoscalePolicy(min_replicas=1, max_replicas=3))


def test_scenario_fleet_field_validation():
    from simple_distributed_machine_learning_tpu.resilience.scenarios import (
        Scenario,
    )
    base = SCENARIOS["fleet-replica-loss"]
    with pytest.raises(ValueError, match="drop supervised"):
        dataclasses.replace(base, supervised=True)
    with pytest.raises(ValueError, match="fleet knobs"):
        dataclasses.replace(SCENARIOS["steady"], min_migrations=1)
    with pytest.raises(ValueError, match="route"):
        dataclasses.replace(base, route="fastest")
    assert isinstance(base, Scenario)


def test_replica_kill_fault_kind_plumbing():
    """The new kind/site parse and the bare-maybe_fire effect: a plan
    outside a fleet still fails loudly instead of silently no-opping."""
    from simple_distributed_machine_learning_tpu.resilience.faults import (
        ReplicaLost,
    )
    plan = faults.FaultPlan.parse("replica-kill@fleet.tick=2,rank=1")
    [spec] = plan.specs
    assert (spec.kind, spec.site, spec.step, spec.rank) == \
        ("replica-kill", "fleet.tick", 2, 1)
    faults.install(plan)
    assert faults.maybe_fire("fleet.tick", step=1, rank=1) == []
    with pytest.raises(ReplicaLost):
        faults.maybe_fire("fleet.tick", step=2, rank=1)
    faults.uninstall()
    with pytest.raises(ValueError, match="unknown fault site"):
        faults.FaultPlan.parse("replica-kill@fleet.tock=2")
    # the kind<->site pairing: any crossed combination would match and
    # count as fired without ever taking effect — refused at parse time
    with pytest.raises(ValueError, match="only interprets"):
        faults.FaultPlan.parse("engine-crash@fleet.tick=2")
    with pytest.raises(ValueError, match="only pairs with"):
        faults.FaultPlan.parse("replica-kill@serve.tick=2")
    # ...but the secondary interpreting site (the adopt/seal race probe
    # in _handoff_step) is a valid pairing
    [spec] = faults.FaultPlan.parse("replica-kill@fleet.handoff,rank=0").specs
    assert (spec.kind, spec.site, spec.rank) == \
        ("replica-kill", "fleet.handoff", 0)


# ---------------------------------------------------------------------------
# bench + CLI surface


def test_bench_fleet_availability_under_replica_loss():
    """The bench fleet availability row: a replica loss costs a
    migration, never a completion — availability pins at 1.0."""
    import jax as _jax

    from bench import _measure_fleet_availability
    from simple_distributed_machine_learning_tpu.models.gpt import (
        make_gpt_stages as _mk,
    )

    stages = _mk(_jax.random.key(0), CFG, n_stages=1)[0]
    [row] = _measure_fleet_availability(stages, CFG, n_requests=8,
                                        max_new=6, prompt_lens=(4, 8),
                                        block_size=4, slots=2)
    assert row["availability"] == 1.0 and row["completed"] == 8
    assert row["replica_losses"] == 1 and row["faults_fired"] == 1
    assert row["migrations"] >= 1 and row["shed_deadline"] == 0


def test_serve_replicas_cli(tmp_path, capsys):
    """--serve-replicas end to end: a replica killed mid-serve migrates
    its work, every request completes, exit 0, and the fleet counters
    land in the serve metrics record + Prometheus exposition."""
    from simple_distributed_machine_learning_tpu.cli import main

    tele = str(tmp_path / "tele")
    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--serve-sim", "6", "--serve-rate", "100", "--serve-slots", "2",
          "--serve-max-new", "4", "--serve-block-size", "4",
          "--serve-prefill-chunk", "3", "--serve-replicas", "3",
          "--serve-chaos", "replica-kill@fleet.tick=4",
          "--telemetry-dir", tele])
    out = capsys.readouterr().out
    assert "| serve: 6/6 requests completed" in out
    assert "1 replica loss(es)" in out
    recs = [json.loads(ln) for ln in
            open(os.path.join(tele, "metrics.jsonl"))]
    r = [x for x in recs if x.get("kind") == "serve"][-1]
    assert r["completed"] == 6
    assert r["fleet_replica_losses"] == 1 and r["fleet_migrations"] >= 1
    prom = open(os.path.join(tele, "metrics.prom")).read()
    assert "serve_fleet_replica_losses_total 1" in prom
    journals = sorted(f for f in os.listdir(tele)
                      if f.startswith("journal-r"))
    assert len(journals) == 3


def test_serve_fleet_cli_flag_validation():
    from simple_distributed_machine_learning_tpu.cli import main

    base = ["--rank", "0", "--world_size", "1", "--model", "gpt",
            "--serve-sim", "2"]
    with pytest.raises(SystemExit, match="serve-replicas"):
        main(base + ["--serve-replicas", "-1"])
    with pytest.raises(SystemExit, match="serve-autoscale"):
        main(base + ["--serve-autoscale", "1,3"])
    with pytest.raises(SystemExit, match="bad --serve-autoscale"):
        main(base + ["--serve-replicas", "2", "--serve-autoscale", "x"])
    with pytest.raises(SystemExit, match="outside the"):
        main(base + ["--serve-replicas", "5", "--serve-autoscale", "1,3"])
    with pytest.raises(SystemExit, match="needs --serve-replicas"):
        # a fleet.tick chaos spec without a fleet would never fire: the
        # drill must refuse, not pass vacuously
        main(base + ["--serve-chaos", "replica-kill@fleet.tick=5"])
    with pytest.raises(SystemExit, match="serve-route needs"):
        # a non-default route without a fleet would be silently ignored
        main(base + ["--serve-route", "round-robin"])
