"""LeNet: the reference's own workload (BASELINE config 4)."""

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.lenet import (
    make_lenet_stages,
)
from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import (
    Pipeline,
    fused_reference,
)


def test_lenet_shapes():
    key = jax.random.key(0)
    stages, wire_dim, out_dim = make_lenet_stages(key, 2)
    assert wire_dim == 784 and out_dim == 10
    x = jax.random.normal(key, (4, 28, 28, 1))
    h = stages[0].apply(stages[0].params, x, key, True)
    assert h.shape == (4, 320)
    logp = stages[1].apply(stages[1].params, h, key, True)
    assert logp.shape == (4, 10)
    np.testing.assert_allclose(np.exp(np.asarray(logp)).sum(-1), 1.0, rtol=1e-5)


def test_lenet_pipeline_matches_fused():
    key = jax.random.key(1)
    stages, wire_dim, out_dim = make_lenet_stages(key, 2)
    x = jax.random.normal(key, (8, 28, 28, 1))
    targets = jax.random.randint(key, (8,), 0, 10)

    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=2)
    buf = pipe.init_params()
    loss, logp = pipe.loss_and_logits(buf, x, targets, key, deterministic=True)

    fused = fused_reference(stages)
    want_logp = fused([s.params for s in stages], x, key, True)
    want = nll_loss(want_logp, targets, "mean")
    np.testing.assert_allclose(float(loss), float(want), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(logp), np.asarray(want_logp),
                               rtol=2e-5, atol=2e-5)


def test_lenet_dropout2d_is_stochastic_in_train():
    key = jax.random.key(2)
    stages, wire_dim, out_dim = make_lenet_stages(key, 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim)
    buf = pipe.init_params()
    x = jax.random.normal(key, (4, 28, 28, 1))
    t = jax.random.randint(key, (4,), 0, 10)
    l1 = pipe.loss_and_logits(buf, x, t, jax.random.key(10), False)[0]
    l2 = pipe.loss_and_logits(buf, x, t, jax.random.key(11), False)[0]
    assert float(l1) != float(l2)
