"""Optimizer semantics: torch parity and ZeRO-1 state sharding.

The reference has exactly one optimizer — SGD(momentum) via
DistributedOptimizer (``/root/reference/simple_distributed.py:100-104``);
its parity is pinned end-to-end by tests/test_torch_parity.py. These cover
the extensions: torch-semantics AdamW, and ZeRO-1 sharding of optimizer
state over the data axis (train/optimizer.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import torch

from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.optimizer import (
    adamw,
    sgd,
    shard_opt_state_zero1,
)
from simple_distributed_machine_learning_tpu.train.step import make_train_step


def test_adamw_matches_torch():
    """Same params, same gradient stream -> same trajectory as
    torch.optim.AdamW (decoupled decay, bias correction)."""
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(7, 5)).astype(np.float32)
    grads = [rng.normal(size=(7, 5)).astype(np.float32) for _ in range(6)]

    pt = torch.nn.Parameter(torch.from_numpy(p0.copy()))
    opt_t = torch.optim.AdamW([pt], lr=0.01, betas=(0.9, 0.999), eps=1e-8,
                              weight_decay=0.03)
    for g in grads:
        opt_t.zero_grad()
        pt.grad = torch.from_numpy(g.copy())
        opt_t.step()

    opt = adamw(0.01, weight_decay=0.03)
    p = jnp.asarray(p0)
    state = opt.init(p)
    for g in grads:
        p, state = opt.update(jnp.asarray(g), state, p)

    np.testing.assert_allclose(np.asarray(p), pt.detach().numpy(),
                               rtol=1e-5, atol=1e-6)


def _problem(batch=8):
    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    x = jax.random.normal(jax.random.key(1), (batch, 12))
    y = jax.random.randint(jax.random.key(2), (batch,), 0, 10)
    return stages, wd, od, x, y


def test_zero1_state_is_data_sharded():
    stages, wd, od, x, y = _problem()
    mesh = make_mesh(n_stages=2, n_data=2)
    pipe = Pipeline(stages, mesh, wd, od, n_microbatches=2)
    buf = pipe.init_params()
    opt = sgd(0.1, momentum=0.5)
    state = shard_opt_state_zero1(opt.init(buf), mesh, pipe.param_spec())
    assert "data" in str(jax.tree.leaves(state)[0].sharding.spec)


def test_zero1_trajectory_matches_replicated():
    """Sharding the optimizer state over data is a pure placement change:
    the SGD(momentum) trajectory must be bit-compatible with the replicated
    layout (GSPMD inserts the all-gather; values are unchanged)."""
    stages, wd, od, x, y = _problem()
    mesh = make_mesh(n_stages=2, n_data=2)
    pipe = Pipeline(stages, mesh, wd, od, n_microbatches=2)
    opt = sgd(0.1, momentum=0.5)
    key = jax.random.key(3)

    losses = {}
    for name in ("replicated", "zero1"):
        buf = pipe.init_params()
        state = opt.init(buf)
        if name == "zero1":
            state = shard_opt_state_zero1(state, mesh, pipe.param_spec())
        step = make_train_step(pipe, opt)
        ls = []
        for i in range(4):
            buf, state, loss = step(buf, state, x, y,
                                    jax.random.fold_in(key, i))
            ls.append(float(loss))
        losses[name] = ls
    np.testing.assert_allclose(losses["zero1"], losses["replicated"],
                               rtol=1e-6, atol=1e-6)


def test_adamw_trains_pipeline():
    """AdamW drives the 2-stage pipeline's loss down (state threads through
    the donated compiled step, including the scalar step counter)."""
    stages, wd, od, x, y = _problem(16)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od, n_microbatches=2)
    opt = adamw(5e-3)
    buf = pipe.init_params()
    state = opt.init(buf)
    step = make_train_step(pipe, opt)
    key = jax.random.key(4)
    first = last = None
    for i in range(40):
        buf, state, loss = step(buf, state, x, y, jax.random.fold_in(key, i))
        first = first if first is not None else float(loss)
        last = float(loss)
    assert last < first * 0.7, (first, last)
