"""Real 2-OS-process launch: the reference's own validation story.

The reference's single documented way to run is two processes launched with
``--rank {0,1} --world_size 2 --master_addr localhost``
(``/root/reference/README.txt:19``; ``simple_distributed.py:169-186``). These
tests run THIS framework's CLI the same verbatim way — two separate OS
processes, ``jax.distributed.initialize`` rendezvous over a real TCP
coordinator, gloo cross-process collectives on the CPU backend, the pipeline's
``ppermute`` hops crossing a process boundary — and assert a completed
train+eval epoch with rank-0-only printing (SPMD mapping of the reference's
master-only console, SURVEY §7 hard part (c)).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_rank(rank: int, port: int, extra: list[str],
                 env_extra: dict | None = None) -> subprocess.Popen:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    # one local device per process: the whole point is crossing a REAL
    # process boundary, not the in-process virtual-device fake cluster
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    cmd = [sys.executable, "-m", "simple_distributed_machine_learning_tpu.cli",
           "--rank", str(rank), "--world_size", "2",
           "--master_addr", "localhost", "--master_port", str(port), *extra]
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, env=env,
                            cwd=REPO)


def run_two_ranks(extra: list[str], timeout: int = 420
                  ) -> tuple[subprocess.CompletedProcess, ...]:
    port = _free_port()
    p0 = _launch_rank(0, port, extra)
    p1 = _launch_rank(1, port, extra)
    try:
        out0, err0 = p0.communicate(timeout=timeout)
        out1, err1 = p1.communicate(timeout=timeout)
    finally:
        for p in (p0, p1):
            if p.poll() is None:
                p.kill()
    return (subprocess.CompletedProcess(p0.args, p0.returncode, out0, err0),
            subprocess.CompletedProcess(p1.args, p1.returncode, out1, err1))


def test_two_process_launch_trains_and_rank0_prints(tmp_path):
    r0, r1 = run_two_ranks([
        "--model", "mlp", "--mlp-dims", "784,64,10", "--epochs", "1",
        "--data-root", str(tmp_path / "nodata"),  # deterministic synthetic
    ])
    assert r0.returncode == 0, f"rank0 failed:\n{r0.stderr[-3000:]}"
    assert r1.returncode == 0, f"rank1 failed:\n{r1.stderr[-3000:]}"
    # rendezvous happened and a full epoch ran: reference-format console
    assert "Train Epoch: 1" in r0.stdout
    assert "Test set: Average loss:" in r0.stdout
    # the final loss is finite (training actually computed, not NaN'd)
    last = [ln for ln in r0.stdout.splitlines() if "Loss:" in ln][-1]
    assert "nan" not in last.lower()
    # SPMD mapping of the reference's master-only console: process 0 prints,
    # process 1 is silent (trainer.is_main)
    assert "Train Epoch" not in r1.stdout
    assert "Test set" not in r1.stdout


def test_two_process_launch_reference_workload_lenet(tmp_path):
    """The reference's own model family (conv front / fc back split across
    the two processes) under the same verbatim launch line."""
    r0, r1 = run_two_ranks([
        "--epochs", "1",                       # default --model lenet
        "--data-root", str(tmp_path / "nodata"),
    ], timeout=560)
    assert r0.returncode == 0, f"rank0 failed:\n{r0.stderr[-3000:]}"
    assert r1.returncode == 0, f"rank1 failed:\n{r1.stderr[-3000:]}"
    assert "Train Epoch: 1" in r0.stdout
    assert "Test set: Average loss:" in r0.stdout
    assert "Train Epoch" not in r1.stdout
