"""Real 2-OS-process launch: the reference's own validation story.

The reference's single documented way to run is two processes launched with
``--rank {0,1} --world_size 2 --master_addr localhost``
(``/root/reference/README.txt:19``; ``simple_distributed.py:169-186``). These
tests run THIS framework's CLI the same verbatim way — two separate OS
processes, ``jax.distributed.initialize`` rendezvous over a real TCP
coordinator, gloo cross-process collectives on the CPU backend, the pipeline's
``ppermute`` hops crossing a process boundary — and assert a completed
train+eval epoch with rank-0-only printing (SPMD mapping of the reference's
master-only console, SURVEY §7 hard part (c)).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # real OS-process launches: per-round gate

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch_rank(rank: int, port: int, extra: list[str],
                 env_extra: dict | None = None, world_size: int = 2,
                 hb_port: int | None = None,
                 stdout=subprocess.PIPE, stderr=subprocess.PIPE
                 ) -> subprocess.Popen:
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    # one local device per process: the whole point is crossing a REAL
    # process boundary, not the in-process virtual-device fake cluster
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    cmd = [sys.executable, "-m", "simple_distributed_machine_learning_tpu.cli",
           "--rank", str(rank), "--world_size", str(world_size),
           "--master_addr", "localhost", "--master_port", str(port), *extra]
    if hb_port is not None:
        cmd += ["--heartbeat-port", str(hb_port)]
    return subprocess.Popen(cmd, stdout=stdout, stderr=stderr, text=True,
                            env=env, cwd=REPO)


def run_ranks(extra: list[str], timeout: int = 420, world_size: int = 2,
              env_extra: dict | None = None
              ) -> tuple[subprocess.CompletedProcess, ...]:
    port, hb_port = _free_port(), _free_port()
    procs = [_launch_rank(r, port, extra, env_extra=env_extra,
                          world_size=world_size,
                          hb_port=hb_port) for r in range(world_size)]
    results = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            results.append(
                subprocess.CompletedProcess(p.args, p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return tuple(results)


def run_two_ranks(extra: list[str], timeout: int = 420
                  ) -> tuple[subprocess.CompletedProcess, ...]:
    return run_ranks(extra, timeout=timeout, world_size=2)


def test_two_process_launch_trains_and_rank0_prints(tmp_path):
    r0, r1 = run_two_ranks([
        "--model", "mlp", "--mlp-dims", "784,64,10", "--epochs", "1",
        "--data-root", str(tmp_path / "nodata"),  # deterministic synthetic
    ])
    assert r0.returncode == 0, f"rank0 failed:\n{r0.stderr[-3000:]}"
    assert r1.returncode == 0, f"rank1 failed:\n{r1.stderr[-3000:]}"
    # rendezvous happened and a full epoch ran: reference-format console
    assert "Train Epoch: 1" in r0.stdout
    assert "Test set: Average loss:" in r0.stdout
    # the final loss is finite (training actually computed, not NaN'd)
    last = [ln for ln in r0.stdout.splitlines() if "Loss:" in ln][-1]
    assert "nan" not in last.lower()
    # SPMD mapping of the reference's master-only console: process 0 prints,
    # process 1 is silent (trainer.is_main)
    assert "Train Epoch" not in r1.stdout
    assert "Test set" not in r1.stdout


def test_two_process_launch_reference_workload_lenet(tmp_path):
    """The reference's own model family (conv front / fc back split across
    the two processes) under the same verbatim launch line."""
    r0, r1 = run_two_ranks([
        "--epochs", "1",                       # default --model lenet
        "--data-root", str(tmp_path / "nodata"),
    ], timeout=560)
    assert r0.returncode == 0, f"rank0 failed:\n{r0.stderr[-3000:]}"
    assert r1.returncode == 0, f"rank1 failed:\n{r1.stderr[-3000:]}"
    assert "Train Epoch: 1" in r0.stdout
    assert "Test set: Average loss:" in r0.stdout
    assert "Train Epoch" not in r1.stdout


def test_two_process_launch_gpt(tmp_path):
    """The GPT family end to end across a real process boundary: embedding
    stage on rank 0, head stage on rank 1, per-token LM loss, GPipe
    microbatching — same verbatim launch line. --generate additionally
    runs the pipeline-parallel KV-cache decoder across the SAME process
    boundary (stage-sharded params, token relay over the cross-process
    ring) and prints the sample on rank 0 only."""
    r0, r1 = run_two_ranks([
        "--model", "gpt", "--epochs", "1", "--microbatches", "2",
        "--batch-size", "32", "--generate", "8",
        "--data-root", str(tmp_path / "nodata"),
    ], timeout=560)
    assert r0.returncode == 0, f"rank0 failed:\n{r0.stderr[-3000:]}"
    assert r1.returncode == 0, f"rank1 failed:\n{r1.stderr[-3000:]}"
    assert "Train Epoch: 1" in r0.stdout
    assert "Test set: Average loss:" in r0.stdout
    assert "Train Epoch" not in r1.stdout
    last = [ln for ln in r0.stdout.splitlines() if "Loss:" in ln][-1]
    assert "nan" not in last.lower()
    assert "| sample tokens" in r0.stdout
    assert "| sample tokens" not in r1.stdout


def test_dead_peer_aborts_rank0(tmp_path):
    """SURVEY §5.3: kill rank 1 mid-run; rank 0 must exit nonzero promptly
    instead of hanging forever inside a collective (the reference hangs:
    rpc_timeout=0, simple_distributed.py:36,167).

    Detection is redundant by design and the winner is a race: the heartbeat
    watchdog's EOF reader (utils/failure.py), gloo's own connection-reset
    error surfacing as a JaxRuntimeError, or the jax coordination service's
    fatal heartbeat timeout. Any of them is a correct prompt abort; the
    watchdog exists for the transports/stalls the runtime does NOT detect
    (deterministically unit-tested in tests/test_failure.py)."""
    import signal
    import time

    port, hb_port = _free_port(), _free_port()
    out_path = tmp_path / "r0.log"
    extra = ["--model", "mlp", "--mlp-dims", "784,64,10",
             "--epochs", "500",                 # far more work than we allow
             "--data-root", str(tmp_path / "nodata"),
             "--peer-timeout", "15"]
    with open(out_path, "w") as f0:
        p0 = _launch_rank(0, port, extra, hb_port=hb_port,
                          stdout=f0, stderr=subprocess.STDOUT)
        p1 = _launch_rank(1, port, extra, hb_port=hb_port,
                          stdout=subprocess.DEVNULL,
                          stderr=subprocess.DEVNULL)
        try:
            # wait until training is actually underway on rank 0
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                if p0.poll() is not None:
                    raise AssertionError(
                        f"rank0 exited early:\n{out_path.read_text()[-3000:]}")
                if "Train Epoch" in out_path.read_text():
                    break
                time.sleep(1.0)
            else:
                raise AssertionError("training never started")
            p1.send_signal(signal.SIGKILL)
            rc = p0.wait(timeout=120)
        finally:
            for p in (p0, p1):
                if p.poll() is None:
                    p.kill()
    assert rc not in (0, None), "rank 0 must fail once its peer is gone"
    log = out_path.read_text()
    assert ("aborting run" in log                      # our watchdog won
            or "Connection reset by peer" in log       # gloo detected it
            or "heartbeat timeout" in log), (          # coordination service
        f"expected a dead-peer diagnostic:\n{log[-2000:]}")


def test_frozen_peer_aborts_run(tmp_path):
    """A SIGSTOPped (frozen, not dead) rank is detected by its own monitor
    subprocess via /proc state and converted into a run abort — the case
    neither socket EOF nor the jax coordination heartbeat catches quickly."""
    import signal
    import time

    port, hb_port = _free_port(), _free_port()
    out_path = tmp_path / "r0.log"
    extra = ["--model", "mlp", "--mlp-dims", "784,64,10",
             "--epochs", "500",
             "--data-root", str(tmp_path / "nodata"),
             "--peer-timeout", "8"]
    with open(out_path, "w") as f0:
        p0 = _launch_rank(0, port, extra, hb_port=hb_port,
                          stdout=f0, stderr=subprocess.STDOUT)
        p1 = _launch_rank(1, port, extra, hb_port=hb_port,
                          stdout=subprocess.DEVNULL,
                          stderr=subprocess.DEVNULL)
        try:
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline:
                if p0.poll() is not None:
                    raise AssertionError(
                        f"rank0 exited early:\n{out_path.read_text()[-3000:]}")
                if "Train Epoch" in out_path.read_text():
                    break
                time.sleep(1.0)
            else:
                raise AssertionError("training never started")
            p1.send_signal(signal.SIGSTOP)
            rc = p0.wait(timeout=120)
        finally:
            for p in (p0, p1):
                if p.poll() is None:
                    try:
                        p.send_signal(signal.SIGCONT)
                    except ProcessLookupError:
                        pass
                    p.kill()
    assert rc not in (0, None), "rank 0 must fail once its peer is frozen"


def test_checkpoint_resume_across_restart_bit_exact(tmp_path):
    """Multi-process checkpointing end to end: a 2-process run that is
    stopped after epoch 1 and relaunched must resume (not restart) and land
    on the BIT-EXACT state a straight-through 2-epoch run produces — the
    gather inside save_checkpoint is a collective both processes drive, and
    restore must reload step count and RNG position exactly."""
    import numpy as np

    common = ["--model", "mlp", "--mlp-dims", "784,64,10",
              "--data-root", str(tmp_path / "nodata")]

    dir_a = str(tmp_path / "ckpt_straight")
    r0, r1 = run_two_ranks(common + ["--epochs", "2",
                                     "--checkpoint-dir", dir_a])
    assert r0.returncode == 0, f"straight run failed:\n{r0.stderr[-3000:]}"

    dir_b = str(tmp_path / "ckpt_resumed")
    r0, r1 = run_two_ranks(common + ["--epochs", "1",
                                     "--checkpoint-dir", dir_b])
    assert r0.returncode == 0, f"first leg failed:\n{r0.stderr[-3000:]}"
    r0, r1 = run_two_ranks(common + ["--epochs", "2",
                                     "--checkpoint-dir", dir_b])
    assert r0.returncode == 0, f"resumed leg failed:\n{r0.stderr[-3000:]}"
    assert "resumed from" in r0.stdout
    # resumed run trains ONLY epoch 2
    assert "Train Epoch: 2" in r0.stdout
    assert "Train Epoch: 1" not in r0.stdout

    za = np.load(os.path.join(dir_a, "state.npz"))
    zb = np.load(os.path.join(dir_b, "state.npz"))
    assert np.array_equal(za["params"], zb["params"]), \
        "resumed params differ from the straight-through run"
    assert np.array_equal(za["opt_0"], zb["opt_0"]), \
        "resumed optimizer state differs from the straight-through run"


def test_checkpoint_with_zero1_sharded_state(tmp_path):
    """Checkpoint save/resume when the optimizer state is ZeRO-1-sharded:
    the collective gather must reassemble data-sharded leaves, and resume
    must re-place them onto the sharded layout."""
    import numpy as np

    # dp=2 across the two processes: the zero1 state is genuinely sharded
    # over a process boundary, so save exercises the collective gather of
    # non-addressable data-sharded leaves
    common = ["--model", "mlp", "--mlp-dims", "784,64,10",
              "--stages", "1", "--dp", "2", "--zero1",
              "--data-root", str(tmp_path / "nodata")]

    dir_a = str(tmp_path / "ckpt_z1_straight")
    r0, r1 = run_two_ranks(common + ["--epochs", "2",
                                     "--checkpoint-dir", dir_a])
    assert r0.returncode == 0, f"straight run failed:\n{r0.stderr[-3000:]}"
    assert r1.returncode == 0, f"straight rank1 failed:\n{r1.stderr[-3000:]}"

    dir_b = str(tmp_path / "ckpt_z1_resumed")
    r0, r1 = run_two_ranks(common + ["--epochs", "1",
                                     "--checkpoint-dir", dir_b])
    assert r0.returncode == 0, f"first leg failed:\n{r0.stderr[-3000:]}"
    assert r1.returncode == 0, f"first-leg rank1 failed:\n{r1.stderr[-3000:]}"
    r0, r1 = run_two_ranks(common + ["--epochs", "2",
                                     "--checkpoint-dir", dir_b])
    assert r0.returncode == 0, f"resume leg failed:\n{r0.stderr[-3000:]}"
    assert r1.returncode == 0, f"resume rank1 failed:\n{r1.stderr[-3000:]}"
    assert "resumed from" in r0.stdout
    assert "Train Epoch: 2" in r0.stdout
    assert "Train Epoch: 1" not in r0.stdout   # resumed, not restarted

    # the gathered zero1 state must land bit-exact on the straight-through
    # run's: wrong shard order in the collective gather (or a swapped
    # re-placement on resume) would diverge the momentum/param bytes
    za = np.load(os.path.join(dir_a, "state.npz"))
    zb = np.load(os.path.join(dir_b, "state.npz"))
    assert np.array_equal(za["params"], zb["params"])
    assert np.array_equal(za["opt_0"], zb["opt_0"])


def test_four_process_dp_pp(tmp_path):
    """world_size=4: a dp=2 x pp=2 mesh over four OS processes (one CPU
    device each) completes an epoch with rank-0-only printing — and with
    per-host input sharding: each host materializes only its 1/dp of every
    batch (rows [0,30) of the 60-row batch on the data-shard-0 hosts,
    [30,60) on data-shard-1; asserted via the SDML_DEBUG_SHARDING stderr
    diagnostic, which never touches the reference-format stdout)."""
    rs = run_ranks([
        "--model", "mlp", "--mlp-dims", "784,64,10", "--epochs", "1",
        "--stages", "2", "--dp", "2", "--microbatches", "2",
        "--data-root", str(tmp_path / "nodata"),
    ], timeout=560, world_size=4, env_extra={"SDML_DEBUG_SHARDING": "1"})
    assert rs[0].returncode == 0, f"rank0 failed:\n{rs[0].stderr[-3000:]}"
    for r in rs[1:]:
        assert r.returncode == 0, f"peer failed:\n{r.stderr[-3000:]}"
        assert "Train Epoch" not in r.stdout
    assert "Train Epoch: 1" in rs[0].stdout
    assert "Test set: Average loss:" in rs[0].stdout
    # device order is data-major: ranks 0,1 = data shard 0, ranks 2,3 =
    # data shard 1; every host holds exactly half the 60-row global batch
    for rank, want in [(0, "[0,30) of 60"), (1, "[0,30) of 60"),
                       (2, "[30,60) of 60"), (3, "[30,60) of 60")]:
        assert f"| host {rank}: input rows {want}" in rs[rank].stderr, (
            rank, rs[rank].stderr[-1500:])


def test_two_process_launch_1f1b(tmp_path):
    """The 1F1B schedule (non-interleaved PipeDream-flush) across a REAL
    process boundary: both
    rings (forward activations, backward cotangents) cross the gloo
    transport every tick, with the scheduled+clipped optimizer in the same
    compiled step."""
    r0, r1 = run_two_ranks([
        "--model", "mlp", "--mlp-dims", "784,64,10", "--epochs", "1",
        "--microbatches", "4", "--schedule", "1f1b",
        "--lr-schedule", "warmup-cosine", "--warmup-steps", "10",
        "--clip-norm", "1.0",
        "--data-root", str(tmp_path / "nodata"),
    ])
    assert r0.returncode == 0, f"rank0 failed:\n{r0.stderr[-3000:]}"
    assert r1.returncode == 0, f"rank1 failed:\n{r1.stderr[-3000:]}"
    assert "Test set: Average loss:" in r0.stdout
    last = [ln for ln in r0.stdout.splitlines() if "Loss:" in ln][-1]
    assert "nan" not in last.lower()
