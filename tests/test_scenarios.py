"""Serving scenario suite: arrival patterns, priority/preemption, SLO gates.

The acceptance pins: bursty and multi-tenant arrival patterns are
deterministic under a fixed seed; per-class SLO attainment (TTFT/TPOT) is
computed from the telemetry registry and asserted; and prefill preemption
of best-effort traffic demonstrably protects the interactive class's p95
TTFT versus FCFS — while every preempted request's tokens stay bit-exact
vs its solo decode (preempt-and-recompute is a scheduling change, not a
math change).
"""

import json
import os

import jax
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_cached_decoder,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.resilience.scenarios import (
    SCENARIOS,
    VirtualClock,
    run_scenario,
)
from simple_distributed_machine_learning_tpu.serve import (
    InferenceEngine,
    PriorityScheduler,
    SimConfig,
    TrafficClass,
)
from simple_distributed_machine_learning_tpu.serve.simulator import (
    build_workload,
)

CFG = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
_STAGES = None


def _model():
    global _STAGES
    if _STAGES is None:
        _STAGES = make_gpt_stages(jax.random.key(0), CFG, 2)[0]
    return _STAGES, [s.params for s in _STAGES]


def _solo(stages, params, prompt, n_new, seed, temperature=0.0, top_k=None):
    dec = make_cached_decoder(stages, CFG, len(prompt), n_new,
                              temperature=temperature, top_k=top_k)
    out = dec(params, np.asarray(prompt, np.int32)[None],
              jax.random.key(seed))
    return np.asarray(out)[0, len(prompt):]


def _prompt(n, seed):
    return np.asarray(
        jax.random.randint(jax.random.key(seed), (n,), 0, CFG.vocab),
        np.int32)


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.uninstall()
    yield
    faults.uninstall()


# ---------------------------------------------------------------------------
# workload generation (no model needed)


def test_poisson_workload_unchanged_by_extension():
    """The legacy single-class poisson path must draw the exact rng stream
    the PR-5 simulator drew (arrivals = one vectorized exponential), so
    every existing determinism pin keeps holding."""
    sim = SimConfig(n_requests=6, rate=8.0, seed=3)
    arrivals, specs = build_workload(sim, vocab=32)
    rng = np.random.default_rng(3)
    np.testing.assert_array_equal(
        arrivals, np.cumsum(rng.exponential(1.0 / 8.0, 6)))
    assert all("cls" not in s for s in specs)


@pytest.mark.parametrize("arrival", ["bursty", "diurnal"])
def test_modulated_arrivals_deterministic(arrival):
    sim = SimConfig(n_requests=40, rate=20.0, seed=5, arrival=arrival,
                    burst_factor=6.0, burst_duty=0.2, period_s=1.0)
    a1, s1 = build_workload(sim, vocab=32)
    a2, s2 = build_workload(sim, vocab=32)
    np.testing.assert_array_equal(a1, a2)
    for x, y in zip(s1, s2):
        np.testing.assert_array_equal(x["prompt"], y["prompt"])
        assert x["seed"] == y["seed"]
    assert np.all(np.diff(a1) > 0) and np.all(np.isfinite(a1))


def test_bursty_arrivals_concentrate_in_duty_window():
    sim = SimConfig(n_requests=300, rate=20.0, seed=1, arrival="bursty",
                    burst_factor=6.0, burst_duty=0.2, period_s=1.0)
    arrivals, _ = build_workload(sim, vocab=32)
    in_burst = np.mean((arrivals % sim.period_s)
                       < sim.burst_duty * sim.period_s)
    # 6x rate over 20% of each cycle => far more than 20% of arrivals land
    # inside the duty window
    assert in_burst > 0.5


def test_multi_tenant_class_assignment_seeded():
    classes = (TrafficClass("interactive", weight=0.3, priority=2,
                            max_new_tokens=4, prompt_lens=(4,)),
               TrafficClass("batch", weight=0.7, priority=0))
    sim = SimConfig(n_requests=30, rate=10.0, seed=9, classes=classes)
    _, s1 = build_workload(sim, vocab=32)
    _, s2 = build_workload(sim, vocab=32)
    assert [s["cls"] for s in s1] == [s["cls"] for s in s2]
    counts = {c: sum(1 for s in s1 if s["cls"] == c)
              for c in ("interactive", "batch")}
    assert counts["interactive"] > 0 and counts["batch"] > 0
    assert counts["batch"] > counts["interactive"]       # weight 0.7 vs 0.3
    for s in s1:
        if s["cls"] == "interactive":
            assert s["priority"] == 2 and s["max_new_tokens"] == 4
            assert len(s["prompt"]) == 4


def test_sim_config_validation():
    with pytest.raises(ValueError, match="arrival"):
        SimConfig(arrival="lumpy")
    with pytest.raises(ValueError, match="burst_duty"):
        SimConfig(arrival="bursty", burst_duty=1.5)
    with pytest.raises(ValueError, match="weight"):
        TrafficClass("x", weight=0.0)
    with pytest.raises(ValueError, match="duplicate"):
        SimConfig(classes=(TrafficClass("a"), TrafficClass("a")))


def test_virtual_clock_semantics():
    clock = VirtualClock(per_call_s=0.5)
    assert clock() == 0.5 and clock() == 1.0
    clock.sleep(2.0)
    assert clock() == 3.5
    clock.sleep(-1.0)                    # negative sleeps never rewind time
    assert clock() == 4.0
    with pytest.raises(ValueError):
        VirtualClock(per_call_s=0.0)


# ---------------------------------------------------------------------------
# priority scheduling + prefill preemption


def test_preemption_parity_paged():
    """THE preemption correctness pin: an interactive arrival preempts a
    decoding best-effort request (slot + blocks freed mid-flight); the
    victim later re-admits, recomputes K/V for its emitted tokens and
    finishes with tokens BIT-EXACT vs its solo decode — for greedy and
    sampled victims alike."""
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=2,
                          scheduler=PriorityScheduler, block_size=4,
                          prefill_chunk=3)
    b1 = eng.submit(_prompt(6, 1), max_new_tokens=14, seed=11, cls="batch")
    b2 = eng.submit(_prompt(8, 2), max_new_tokens=14, seed=12, cls="batch",
                    temperature=0.8, top_k=5)
    for _ in range(6):
        eng.step()
    it = eng.submit(_prompt(4, 3), max_new_tokens=5, seed=13,
                    cls="interactive", priority=2)
    eng.drain()
    assert b1.n_preempted + b2.n_preempted >= 1
    assert it.n_preempted == 0
    for h, (p, n, s, t, k) in [(b1, (_prompt(6, 1), 14, 11, 0.0, None)),
                               (b2, (_prompt(8, 2), 14, 12, 0.8, 5)),
                               (it, (_prompt(4, 3), 5, 13, 0.0, None))]:
        want = _solo(stages, params, p, n, s, temperature=t, top_k=k)
        np.testing.assert_array_equal(np.asarray(h.tokens), want,
                                      err_msg=f"request {h.rid}")


def test_preemption_parity_dense_layout():
    """Same pin on the dense slot-row layout (whole-prompt re-prefill with
    the sample discarded)."""
    stages, params = _model()
    eng = InferenceEngine(stages, CFG, n_slots=2,
                          scheduler=PriorityScheduler, kv_layout="dense")
    b1 = eng.submit(_prompt(6, 1), max_new_tokens=12, seed=11, cls="batch")
    b2 = eng.submit(_prompt(8, 2), max_new_tokens=12, seed=12, cls="batch")
    for _ in range(4):
        eng.step()
    it = eng.submit(_prompt(4, 3), max_new_tokens=5, seed=13,
                    cls="interactive", priority=2)
    eng.drain()
    assert b1.n_preempted + b2.n_preempted >= 1
    for h, (p, n, s) in [(b1, (_prompt(6, 1), 12, 11)),
                         (b2, (_prompt(8, 2), 12, 12)),
                         (it, (_prompt(4, 3), 5, 13))]:
        np.testing.assert_array_equal(np.asarray(h.tokens),
                                      _solo(stages, params, p, n, s),
                                      err_msg=f"request {h.rid}")


def test_priority_never_preempts_equal_or_higher():
    stages, _ = _model()
    eng = InferenceEngine(stages, CFG, n_slots=1,
                          scheduler=PriorityScheduler, block_size=4)
    a = eng.submit(_prompt(4, 1), max_new_tokens=10, seed=1,
                   cls="interactive", priority=2)
    eng.step()
    b = eng.submit(_prompt(4, 2), max_new_tokens=4, seed=2,
                   cls="interactive", priority=2)
    eng.drain()
    assert a.n_preempted == 0 and b.n_preempted == 0
    # equal priority: the resident request ran to completion first
    assert a.done_time <= b.first_token_time


# ---------------------------------------------------------------------------
# SLO-gated scenarios


def test_preemption_protects_interactive_p95_ttft_vs_fcfs():
    """The scenario-level acceptance pin, both sides: under the bursty
    two-tenant load, priority+preemption attains the interactive TTFT SLO
    while plain FCFS misses it — and the p95 gap is wide, not marginal."""
    stages, _ = _model()
    prio = run_scenario("burst-interactive", stages, CFG)
    fcfs = run_scenario("burst-interactive", stages, CFG, scheduler="fcfs")
    assert prio["all_completed"] and fcfs["all_completed"]
    p_att = prio["slo"]["interactive"]
    f_att = fcfs["slo"]["interactive"]
    assert prio["slo_ok"] and p_att["ok"]
    assert not fcfs["slo_ok"] and not f_att["ok"]
    assert prio.get("preemptions", 0) > 0 and "preemptions" not in fcfs
    # demonstrable protection: p95 TTFT at least 3x better under priority
    assert p_att["ttft_ms_p95"] * 3 < f_att["ttft_ms_p95"]
    # attainment came from the registry histograms
    assert p_att["ttft_attainment"] >= 0.9
    assert f_att["ttft_attainment"] < 0.9


def test_scenarios_deterministic_under_fixed_seed():
    """Byte-identical reports across runs — the virtual clock removes the
    host from the measurement, so CI can gate on exact numbers."""
    stages, _ = _model()
    for name in ("burst-interactive", "multi-tenant"):
        r1 = run_scenario(name, stages, CFG)
        r2 = run_scenario(name, stages, CFG)
        assert json.dumps(r1, sort_keys=True) == \
            json.dumps(r2, sort_keys=True), name


def test_steady_scenario_meets_slo():
    stages, _ = _model()
    rep = run_scenario("steady", stages, CFG)
    assert rep["slo_ok"] and rep["all_completed"]
    assert rep["slo"]["interactive"]["ttft_attainment"] == 1.0


def test_slow_tick_fault_scenario_holds_slo():
    """Fault + load composed: the injected slow-tick schedule fires (device
    degradation is really in the run) and the SLOs still hold — CI's
    'stayed within SLO under this fault + this load' gate."""
    stages, _ = _model()
    rep = run_scenario("burst-slow-tick", stages, CFG)
    assert rep["faults"]["total_fired"] == 10
    assert rep["slo_ok"] and rep["all_completed"]
    assert faults.active() is None       # runner uninstalled its plan


def test_run_scenario_emits_gateable_records(tmp_path):
    """The artifact CI parses: metrics.jsonl carries the serve record (with
    per-class blocks) and a kind=scenario record with slo_ok + per-class
    attainment; metrics.prom exposes the class series."""
    stages, _ = _model()
    rep = run_scenario("multi-tenant", stages, CFG, outdir=str(tmp_path))
    assert rep["slo_ok"]
    recs = [json.loads(line)
            for line in open(os.path.join(str(tmp_path), "metrics.jsonl"))]
    serve = [r for r in recs if r.get("kind") == "serve"]
    scen = [r for r in recs if r.get("kind") == "scenario"]
    assert serve and scen
    assert "per_class" in serve[-1]
    assert set(serve[-1]["per_class"]) == {"interactive", "standard",
                                           "batch"}
    s = scen[-1]
    assert s["scenario"] == "multi-tenant" and s["slo_ok"] is True
    for cls in ("interactive", "standard"):
        assert s["slo"][cls]["ttft_attainment"] is not None
        assert s["slo"][cls]["ok"] is True
    prom = open(os.path.join(str(tmp_path), "metrics.prom")).read()
    assert 'serve_class_ttft_ms{class="interactive",quantile="0.95"}' in prom
    assert "serve_class_completed_total" in prom


def test_unknown_scenario_rejected():
    stages, _ = _model()
    with pytest.raises(ValueError, match="unknown scenario"):
        run_scenario("nope", stages, CFG)
    assert set(SCENARIOS) == {"steady", "burst-interactive", "multi-tenant",
                              "burst-slow-tick", "crash-serve",
                              "overload-shed", "fleet-replica-loss",
                              "hot-prefix-skew", "fleet-autoscale-diurnal",
                              "disagg-prefill-heavy", "offload-churn",
                              "handoff-replica-loss", "hot-adapter-churn"}


# ---------------------------------------------------------------------------
# crash-restartable serving + overload shedding (ISSUE 10)


def test_crash_serve_scenario_recovers_within_slo():
    """The chaos-serve gate: an engine crash fires mid-run, the serve
    supervisor restarts exactly once, ALL requests complete, and the
    interactive SLOs hold through the restart — pinned on the virtual
    clock's exact numbers."""
    stages, _ = _model()
    rep = run_scenario("crash-serve", stages, CFG)
    assert rep["slo_ok"] and rep["all_completed"]
    assert rep["restarts"] == 1 and rep["supervised"]
    assert rep["faults"]["total_fired"] == 1
    assert rep["supervisor_state"] == "running"
    att = rep["slo"]["interactive"]
    # exact virtual-clock numbers: recovery costs a few ticks, not the SLO
    assert att["ttft_attainment"] == 1.0 and att["tpot_attainment"] == 1.0
    assert att["ttft_ms_p95"] == 23.16
    assert rep["recovered_requests"] > 0
    assert faults.active() is None


def test_crash_serve_scenario_gate_requires_a_restart():
    """min_restarts is the dynamic twin of the FaultSpec site check: the
    same scenario run WITHOUT supervision must refuse (restarts live in
    the supervisor), and a supervised run whose fault never fired fails
    the gate instead of passing vacuously."""
    import dataclasses as _dc

    from simple_distributed_machine_learning_tpu.resilience.scenarios import (
        Scenario,
    )

    stages, _ = _model()
    # chaos stripped: no restart happens -> min_restarts gates slo_ok False
    quiet = _dc.replace(SCENARIOS["crash-serve"], chaos=None)
    rep = run_scenario(quiet, stages, CFG)
    assert rep["restarts"] == 0 and rep["all_completed"]
    assert not rep["slo_ok"]
    with pytest.raises(ValueError, match="min_restarts"):
        Scenario(name="x", description="", sim=SCENARIOS["steady"].sim,
                 min_restarts=1)


def test_overload_shed_protects_interactive_vs_fcfs_baseline():
    """THE overload acceptance pin, both sides, exact virtual-clock
    numbers: at >1.5x capacity with per-class deadlines the supervisor
    sheds expired/over-budget work and the interactive class attains its
    SLOs (gate passes with every request accounted for); the no-deadline
    FCFS baseline completes everything but blows interactive TTFT by an
    order of magnitude and fails the same gate."""
    stages, _ = _model()
    rep = run_scenario("overload-shed", stages, CFG)
    assert rep["slo_ok"] and rep["supervised"]
    assert rep["completed"] + rep["shed"] == rep["n_requests"] == 36
    assert rep["completed"] == 11 and rep["shed"] == 25
    assert rep["shed_by_reason"] == {"backpressure": 5, "class": 18,
                                     "deadline": 2}
    # the 18 class sheds prove the best-effort lockout ENGAGED mid-burst;
    # the final gauge reads 0 because the hysteresis correctly lifts the
    # mode once the backlog drains (the latch regression's pin)
    assert rep["degraded"] == 0
    att = rep["slo"]["interactive"]
    assert att["ttft_attainment"] == 1.0 and att["ok"]
    assert att["ttft_ms_p95"] == 75.651

    base = run_scenario("overload-shed", stages, CFG, scheduler="fcfs",
                        supervised=False)
    assert not base["slo_ok"]
    assert base["all_completed"] and base["shed"] == 0   # nothing enforced
    f_att = base["slo"]["interactive"]
    assert f_att["ttft_attainment"] == 0.0 and not f_att["ok"]
    assert f_att["ttft_ms_p95"] == 995.326               # ~10x the target
    # the pinned gap: shedding is what buys the attainment
    assert att["ttft_ms_p95"] * 10 < f_att["ttft_ms_p95"]


def test_supervised_scenarios_deterministic():
    """The new supervised scenarios produce byte-identical reports across
    runs — journaling and recovery do not perturb the virtual clock's
    determinism, so CI can gate on their exact numbers too."""
    stages, _ = _model()
    for name in ("crash-serve", "overload-shed"):
        r1 = run_scenario(name, stages, CFG)
        r2 = run_scenario(name, stages, CFG)
        assert json.dumps(r1, sort_keys=True) == \
            json.dumps(r2, sort_keys=True), name
