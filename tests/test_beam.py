"""Beam-search decoder: greedy equivalence, score correctness, improvement."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.beam import (
    make_beam_decoder,
)
from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_cached_decoder,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.parallel.pipeline import (
    fused_reference,
)

CFG = GPTConfig(vocab=32, seq_len=24, d_model=32, n_heads=2, n_layers=2)


def _model():
    stages, _, _ = make_gpt_stages(jax.random.key(0), CFG, 2)
    return stages, [s.params for s in stages]


def _seq_logprob(stages, params, seq, prompt_len):
    """Cumulative log-prob of seq's generated suffix under the model."""
    fused = fused_reference(stages)
    buf = np.zeros((seq.shape[0], CFG.seq_len), np.float32)
    buf[:, :seq.shape[1]] = np.asarray(seq)
    logp = np.asarray(fused(params, jnp.asarray(buf), jax.random.key(0),
                            True))
    total = 0.0
    for b in range(seq.shape[0]):
        for pos in range(prompt_len - 1, seq.shape[1] - 1):
            total += logp[b, pos, int(seq[b, pos + 1])]
    return total


def test_beam_size_1_is_greedy():
    stages, params = _model()
    prompt = jax.random.randint(jax.random.key(1), (3, 5), 0, CFG.vocab)
    want = make_cached_decoder(stages, CFG, 5, 9)(
        params, prompt, jax.random.key(0))
    got, _ = make_beam_decoder(stages, CFG, 5, 9, beam_size=1)(
        params, prompt, jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_beam_scores_are_true_cumulative_logprobs():
    """The returned score must equal the model's own log-prob of the
    returned sequence — recomputed independently via the fused forward."""
    stages, params = _model()
    prompt = jax.random.randint(jax.random.key(2), (2, 4), 0, CFG.vocab)
    toks, scores = make_beam_decoder(stages, CFG, 4, 8, beam_size=3)(
        params, prompt, jax.random.key(0))
    toks = np.asarray(toks)
    for b in range(2):
        want = _seq_logprob(stages, params, toks[b:b + 1], 4)
        np.testing.assert_allclose(float(scores[b]), want, rtol=2e-4,
                                   atol=2e-4)


def test_full_width_beam_is_exhaustive_argmax():
    """With beam_size = vocab and n_new = 2 every 2-token continuation
    survives the first expansion, so beam search must return the TRUE
    argmax over all vocab^2 continuations — verified against brute-force
    enumeration scored by the fused model. (Note a fixed-width beam does
    NOT guarantee beating greedy in general — the greedy prefix can be
    pruned mid-search — so exhaustive equivalence is the sound property to
    pin, not greedy-dominance.)"""
    stages, params = _model()
    V = CFG.vocab
    t0 = 4
    prompt = jax.random.randint(jax.random.key(3), (1, t0), 0, V)
    toks, score = make_beam_decoder(stages, CFG, t0, 2, beam_size=V)(
        params, prompt, jax.random.key(0))

    # brute force: score(t1, t2) = logp(prompt)[t1] + logp(prompt+t1)[t2]
    fused = fused_reference(stages)

    def logp_at(rows, pos):
        buf = np.zeros((rows.shape[0], CFG.seq_len), np.float32)
        buf[:, :rows.shape[1]] = rows
        out = fused(params, jnp.asarray(buf), jax.random.key(0), True)
        return np.asarray(out)[:, pos]

    first = logp_at(np.asarray(prompt, np.float32), t0 - 1)[0]     # [V]
    ext = np.repeat(np.asarray(prompt), V, axis=0)
    ext = np.concatenate([ext, np.arange(V)[:, None]], axis=1)
    second = logp_at(ext.astype(np.float32), t0)                   # [V, V]
    table = first[:, None] + second
    b1, b2 = np.unravel_index(np.argmax(table), table.shape)
    np.testing.assert_array_equal(np.asarray(toks)[0, t0:],
                                  [b1, b2])
    np.testing.assert_allclose(float(score[0]), table[b1, b2],
                               rtol=2e-4, atol=2e-4)


def test_beam_validation():
    stages, _ = _model()
    with pytest.raises(ValueError, match="beam_size"):
        make_beam_decoder(stages, CFG, 4, 4, beam_size=0)
    with pytest.raises(ValueError, match="exceeds the model's sequence"):
        make_beam_decoder(stages, CFG, 20, 9)
    with pytest.raises(ValueError, match="eos_id"):
        make_beam_decoder(stages, CFG, 4, 4, eos_id=CFG.vocab)
    with pytest.raises(ValueError, match="eos_id"):
        make_beam_decoder(stages, CFG, 4, 4, eos_id=-1)


def test_beam_eos_terminates_greedy_path():
    """beam_size=1 with eos_id: tokens match the greedy cached decode up to
    and including the FIRST eos, then eos-pad; the score freezes at the
    finished prefix's cumulative log-prob (verified independently)."""
    stages, params = _model()
    prompt = jax.random.randint(jax.random.key(4), (2, 5), 0, CFG.vocab)
    greedy = np.asarray(make_cached_decoder(stages, CFG, 5, 8)(
        params, prompt, jax.random.key(0)))
    eos = int(greedy[0, 5 + 2])          # an eos greedy actually emits
    toks, scores = make_beam_decoder(stages, CFG, 5, 8, beam_size=1,
                                     eos_id=eos)(
        params, prompt, jax.random.key(0))
    toks = np.asarray(toks)
    for b in range(2):
        want = greedy[b, 5:]
        hits = np.where(want == eos)[0]
        cut = int(hits[0]) + 1 if len(hits) else 8
        np.testing.assert_array_equal(toks[b, 5:5 + cut], want[:cut])
        assert (toks[b, 5 + cut:] == eos).all()     # eos-padded tail
        # frozen score == the model's own log-prob of the finished prefix
        ref = _seq_logprob(stages, params, toks[b:b + 1, :5 + cut], 5)
        np.testing.assert_allclose(float(scores[b]), ref, rtol=2e-4,
                                   atol=2e-4)


def test_beam_eos_unfinished_beams_keep_searching():
    """An eos_id no beam emits must not change the no-eos result (the
    finished-beam machinery is inert until an EOS actually fires)."""
    stages, params = _model()
    prompt = jax.random.randint(jax.random.key(5), (2, 4), 0, CFG.vocab)
    base_t, base_s = make_beam_decoder(stages, CFG, 4, 6, beam_size=3)(
        params, prompt, jax.random.key(0))
    base_t = np.asarray(base_t)
    unused = [v for v in range(CFG.vocab) if v not in base_t][0]
    got_t, got_s = make_beam_decoder(stages, CFG, 4, 6, beam_size=3,
                                     eos_id=unused)(
        params, prompt, jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(got_t), base_t)
    np.testing.assert_allclose(np.asarray(got_s), np.asarray(base_s))


def test_beam_prompt_batch_matches_individual():
    """B>1 prompt batches are independent: batched beam decode equals each
    prompt decoded alone (same beams, same scores)."""
    stages, params = _model()
    prompt = jax.random.randint(jax.random.key(6), (3, 5), 0, CFG.vocab)
    dec = make_beam_decoder(stages, CFG, 5, 6, beam_size=3)
    toks_b, scores_b = dec(params, prompt, jax.random.key(0))
    for b in range(3):
        t1, s1 = dec(params, prompt[b:b + 1], jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(toks_b)[b],
                                      np.asarray(t1)[0])
        np.testing.assert_allclose(float(scores_b[b]), float(s1[0]),
                                   rtol=1e-5, atol=1e-5)
