"""Memory-flat eval engine (Pipeline.eval_metrics): parity + memory.

The eval path used to go through ``loss_and_logits``, whose scan carries the
full ``[M, mb, *out_shape]`` log-probs accumulator replicated across stages —
for a vocab-wide LM, eval would OOM long before training. ``eval_metrics``
folds each microbatch's log-probs into three scalars inside the scan; these
tests pin (a) exact agreement with metrics computed from the materialized
logits across pp/dp/sp/ep topologies and ragged masks, and (b) that the
compiled program's temp memory actually shrinks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.data.text import synthetic_tokens
from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
from simple_distributed_machine_learning_tpu.parallel.compat import HAS_VMA
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline


def _reference_metrics(pipe, buf, x, y, key, weights):
    """The old eval computation: materialize logits, reduce on the host."""
    _, logp = pipe.loss_and_logits(buf, x, y, key, deterministic=True)
    nll = nll_loss(logp, y, "none")
    w = (jnp.ones((x.shape[0],), jnp.float32) if weights is None
         else weights.astype(jnp.float32))
    wb = jnp.broadcast_to(w.reshape(w.shape + (1,) * (nll.ndim - 1)),
                          nll.shape)
    hit = (logp.argmax(-1) == y) & (wb > 0)
    return (float(jnp.sum(nll * wb)), float(jnp.sum(wb)),
            int(jnp.sum(hit.astype(jnp.int32))))


def _check(pipe, buf, x, y, key, weights, rtol=2e-5):
    want = _reference_metrics(pipe, buf, x, y, key, weights)
    got = pipe.eval_metrics(buf, x, y, key, weights=weights)
    np.testing.assert_allclose(float(got[0]), want[0], rtol=rtol, atol=1e-4)
    np.testing.assert_allclose(float(got[1]), want[1], rtol=0, atol=1e-6)
    # correct-counts are exact int32: require exact agreement
    assert int(got[2]) == want[2], (got, want)


def test_eval_metrics_gpt_pp_dp_weighted():
    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2)
    stages, wire_dim, out_shape = make_gpt_stages(jax.random.key(0), cfg, 2)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=2), wire_dim,
                    out_shape, n_microbatches=2)
    buf = pipe.init_params()
    data = synthetic_tokens(8, cfg.seq_len, cfg.vocab, seed=2)
    x = jnp.asarray(data.x, jnp.float32)
    y = jnp.asarray(data.y)
    _check(pipe, buf, x, y, jax.random.key(3), None)
    # ragged mask: last 3 rows are padding
    mask = (jnp.arange(8) < 5).astype(jnp.float32)
    _check(pipe, buf, x, y, jax.random.key(3), mask)


@pytest.mark.skipif(
    not HAS_VMA,
    reason="branch-divergent ppermute rings deadlock on old jax's XLA:CPU "
           "collective-permute rendezvous")
def test_eval_metrics_gpt_seq_parallel():
    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2,
                    attn_impl="ring", n_seq=2)
    stages, wire_dim, out_shape = make_gpt_stages(jax.random.key(0), cfg, 2)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=1, n_seq=2),
                    wire_dim, out_shape, n_microbatches=2)
    buf = pipe.init_params()
    data = synthetic_tokens(4, cfg.seq_len, cfg.vocab, seed=4)
    _check(pipe, buf, jnp.asarray(data.x, jnp.float32),
           jnp.asarray(data.y), jax.random.key(5), None)


def test_eval_metrics_moe_expert_parallel():
    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2,
                    n_experts=4, n_expert_parallel=2)
    stages, wire_dim, out_shape = make_gpt_stages(jax.random.key(0), cfg, 2)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=1, n_expert=2),
                    wire_dim, out_shape, n_microbatches=1)
    buf = pipe.init_params()
    data = synthetic_tokens(4, cfg.seq_len, cfg.vocab, seed=6)
    _check(pipe, buf, jnp.asarray(data.x, jnp.float32),
           jnp.asarray(data.y), jax.random.key(7), None)


def test_eval_metrics_tensor_parallel():
    """n_model > 1: exercises the metrics path's model-axis replication
    proof (pmean for the float sums, integer psum // n_model for the
    count) on real column->row TP shards."""
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        make_mlp_tp_stages,
    )

    stages, wire_dim, out_dim = make_mlp_tp_stages(
        jax.random.key(0), [8, 16, 12, 16, 10], 2, 2)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=2, n_model=2),
                    wire_dim, out_dim, n_microbatches=2)
    buf = pipe.init_params()
    x = jax.random.normal(jax.random.key(1), (8, 8))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 10)
    _check(pipe, buf, x, y, jax.random.key(3), None)
    mask = (jnp.arange(8) < 7).astype(jnp.float32)
    _check(pipe, buf, x, y, jax.random.key(3), mask)


def test_eval_metrics_classifier_ragged():
    stages, wire_dim, out_dim = make_mlp_stages(
        jax.random.key(0), [12, 16, 10], 2)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=2), wire_dim,
                    out_dim, n_microbatches=2)
    buf = pipe.init_params()
    x = jax.random.normal(jax.random.key(1), (8, 12))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 10)
    mask = (jnp.arange(8) < 6).astype(jnp.float32)
    _check(pipe, buf, x, y, jax.random.key(3), mask)


def test_eval_metrics_trivial_mesh_fused():
    """Single-device fast path agrees with the engine semantics."""
    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2)
    stages, wire_dim, out_shape = make_gpt_stages(jax.random.key(0), cfg, 1)
    mesh = make_mesh(n_stages=1, n_data=1, devices=jax.devices()[:1])
    pipe = Pipeline(stages, mesh, wire_dim, out_shape, n_microbatches=1)
    buf = pipe.init_params()
    data = synthetic_tokens(4, cfg.seq_len, cfg.vocab, seed=8)
    _check(pipe, buf, jnp.asarray(data.x, jnp.float32),
           jnp.asarray(data.y), jax.random.key(9), None)


def test_eval_metrics_memory_smaller_than_logits_path():
    """The compiled metrics program must not carry the [M, mb, T, V] logits
    accumulator: its temp allocation stays well under the logits path's on a
    config where that accumulator dominates (V=512, M=4)."""
    cfg = GPTConfig(vocab=512, seq_len=32, d_model=32, n_heads=2, n_layers=2)
    stages, wire_dim, out_shape = make_gpt_stages(jax.random.key(0), cfg, 2)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=1), wire_dim,
                    out_shape, n_microbatches=4)
    buf = pipe.init_params()
    data = synthetic_tokens(16, cfg.seq_len, cfg.vocab, seed=10)
    x = jnp.asarray(data.x, jnp.float32)
    y = jnp.asarray(data.y)
    key = jax.random.key(11)

    def temp_bytes(fn):
        lowered = jax.jit(fn).lower(buf, x, y, key)
        mem = lowered.compile().memory_analysis()
        if mem is None or not hasattr(mem, "temp_size_in_bytes"):
            pytest.skip("backend exposes no memory analysis")
        return mem.temp_size_in_bytes

    t_metrics = temp_bytes(
        lambda b, xx, yy, k: pipe.eval_metrics(b, xx, yy, k))
    t_logits = temp_bytes(
        lambda b, xx, yy, k: pipe.loss_and_logits(b, xx, yy, k,
                                                  deterministic=True))
    # the logits path carries [M=4, mb=4, T=32, V=512] f32 (~1 MB) in the
    # carry plus its stage-axis psum; the metrics path carries scalars
    assert t_metrics < t_logits, (t_metrics, t_logits)
    acc_bytes = 4 * 4 * 32 * 512 * 4
    assert t_logits - t_metrics > acc_bytes // 2, (t_metrics, t_logits)
