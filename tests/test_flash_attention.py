"""Pallas flash attention vs the dense reference implementation.

Runs the real kernel code path in Pallas interpret mode on CPU (the kernel
compiles through Mosaic unchanged on TPU).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.ops.attention import (
    causal_attention,
    causal_attention_core,
    mha_init,
)
from simple_distributed_machine_learning_tpu.ops.flash_attention import (
    _diag_kv_index,
    flash_attention,
    flash_mha,
)

# the canonical masked-softmax math from ops/attention.py — the kernel is
# verified against the same code every other attention path uses
_dense_reference = causal_attention_core


@pytest.mark.parametrize("bq,bk", [(128, 128), (256, 128), (128, 256),
                                   (512, 1024), (96, 64)])
def test_diag_kv_index_clamp(bq, bk):
    """The causal fetch-elision index map: for q-block j the LAST needed
    k-block covers position j*bq + bq - 1, and every kb beyond it must clamp
    there (same index as the previous iteration ⇒ Mosaic elides the fetch);
    every kb at or before it must pass through unchanged."""
    idx = _diag_kv_index(bq, bk)
    for j in range(6):
        last_needed = (((j + 1) * bq) - 1) // bk
        for kb in range(12):
            i_, got, z = idx(7, j, kb)
            assert (i_, z) == (7, 0)
            assert int(got) == min(kb, last_needed)
        # the block holding the diagonal position is always fetchable
        assert int(idx(0, j, last_needed)[1]) == last_needed


@pytest.mark.parametrize("t,dh,bq,bk", [
    (64, 32, 16, 16),     # blocks divide T
    (48, 32, 16, 16),     # T not a multiple of the block: padding path
    (64, 32, 64, 64),     # single block
    (64, 32, 16, 32),     # block_q < block_k: diagonal crosses mid k-block
    (64, 32, 32, 16),     # block_q > block_k: several k blocks per q block
])
def test_flash_matches_dense_forward(t, dh, bq, bk):
    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (2, 3, t, dh)
    q = jax.random.normal(kq, shape)
    k = jax.random.normal(kk, shape)
    v = jax.random.normal(kv, shape)
    out = flash_attention(q, k, v, bq, bk)
    ref = _dense_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_gradients_match_dense():
    key = jax.random.key(1)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (1, 2, 32, 16)
    q = jax.random.normal(kq, shape)
    k = jax.random.normal(kk, shape)
    v = jax.random.normal(kv, shape)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, 16, 16) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_reference(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("t,bq,bk", [
    (48, 16, 16),     # T not a multiple of the block: backward padding path
    (32, 32, 32),     # single block each way
    (64, 16, 32),     # asymmetric: dq clamp crosses mid k-block
    (64, 32, 16),     # asymmetric: dkv clamp starts mid q-block
])
def test_flash_gradients_match_dense_padded(t, bq, bk):
    key = jax.random.key(7)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (2, 2, t, 16)
    q = jax.random.normal(kq, shape)
    k = jax.random.normal(kk, shape)
    v = jax.random.normal(kv, shape)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, bq, bk) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(_dense_reference(q, k, v) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_bf16_gradients():
    """bf16 cotangents flow through the Pallas backward (f32 accumulation)."""
    key = jax.random.key(8)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (1, 2, 32, 16)
    q = jax.random.normal(kq, shape).astype(jnp.bfloat16)
    k = jax.random.normal(kk, shape).astype(jnp.bfloat16)
    v = jax.random.normal(kv, shape).astype(jnp.bfloat16)

    gf = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, 16, 16).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: jnp.sum(_dense_reference(q, k, v) ** 2),
                  argnums=(0, 1, 2))(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32))
    for a, b in zip(gf, gd):
        assert a.dtype == q.dtype
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b),
                                   rtol=1e-1, atol=1e-1)


def test_flash_mha_matches_causal_attention():
    key = jax.random.key(2)
    d, h, t, b = 64, 4, 32, 2
    params = mha_init(jax.random.key(3), d, h)
    x = jax.random.normal(key, (b, t, d))
    out = flash_mha(params, x, h, block_q=16, block_k=16)
    ref = causal_attention(params, x, h)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_flash_bf16_inputs():
    """bf16 q/k/v accumulate in f32 inside the kernel."""
    key = jax.random.key(4)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (1, 2, 32, 16)
    q = jax.random.normal(kq, shape).astype(jnp.bfloat16)
    k = jax.random.normal(kk, shape).astype(jnp.bfloat16)
    v = jax.random.normal(kv, shape).astype(jnp.bfloat16)
    out = flash_attention(q, k, v, 16, 16)
    assert out.dtype == jnp.bfloat16
    ref = _dense_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                           v.astype(jnp.float32))
    from tolerances import attn_tol

    rtol, atol = attn_tol(jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=rtol, atol=atol)


def test_gpt_flash_gradients_match_dense():
    """End-to-end: a GPT built with attn_impl='flash' produces the same
    parameter GRADIENTS as the dense one — the Pallas backward kernels'
    cotangents flow correctly through QKVO projections, residuals, and the
    LM loss."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        fused_reference,
    )

    key = jax.random.key(9)
    kw = dict(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=1)
    sd, _, _ = make_gpt_stages(key, GPTConfig(**kw), n_stages=1)
    sf, _, _ = make_gpt_stages(key, GPTConfig(attn_impl="flash", **kw),
                               n_stages=1)
    ids = jax.random.randint(jax.random.key(10), (2, 16), 0, 32).astype(
        jnp.float32)
    tgt = jax.random.randint(jax.random.key(11), (2, 16), 0, 32)

    def loss(stages, params):
        logp = fused_reference(stages)(params, ids, jax.random.key(0), True)
        return nll_loss(logp, tgt, "mean")

    gd = jax.grad(lambda p: loss(sd, p))([s.params for s in sd])
    gf = jax.grad(lambda p: loss(sf, p))([s.params for s in sf])
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_gpt_flash_matches_dense_stages():
    """A GPT built with attn_impl='flash' computes the same log-probs."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        fused_reference,
    )

    key = jax.random.key(5)
    kw = dict(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2)
    sd, _, _ = make_gpt_stages(key, GPTConfig(**kw), n_stages=1)
    sf, _, _ = make_gpt_stages(key, GPTConfig(attn_impl="flash", **kw),
                               n_stages=1)
    ids = jax.random.randint(jax.random.key(6), (2, 16), 0, 32).astype(
        jnp.float32)
    out_d = fused_reference(sd)([s.params for s in sd], ids,
                                jax.random.key(0), True)
    out_f = fused_reference(sf)([s.params for s in sf], ids,
                                jax.random.key(0), True)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_d),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
def test_gpt_flash_runs_in_sharded_pipeline(schedule):
    """attn_impl='flash' inside the REAL shard_map pipeline engines
    (check_vma on), under BOTH schedules — GPipe's jax.grad-through-scan
    and 1F1B's hand-scheduled jax.vjp (the kernel's custom_vjp must
    compose with each). Regression for the missing vma declaration on the
    pallas_call out_shape structs, which made every --attn flash pipeline
    run fail to trace. One train step must match the dense build exactly
    (flash is the same math; f32, tiny T)."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )
    from simple_distributed_machine_learning_tpu.train.optimizer import sgd
    from simple_distributed_machine_learning_tpu.train.step import (
        make_train_step,
    )

    kw = dict(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2)
    x = jax.random.randint(jax.random.key(1), (8, 16), 0, 32).astype(
        jnp.float32)
    y = jax.random.randint(jax.random.key(2), (8, 16), 0, 32)
    opt = sgd(0.1, 0.5)

    def one_step(cfg):
        stages, wd, osh = make_gpt_stages(jax.random.key(0), cfg, 2)
        pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=1), wd, osh,
                        n_microbatches=2, schedule=schedule)
        buf = pipe.init_params()
        buf, _, loss = make_train_step(pipe, opt)(
            buf, opt.init(buf), x, y, jax.random.key(3))
        return float(loss), np.asarray(buf)

    lf, bf = one_step(GPTConfig(attn_impl="flash", flash_block_q=16,
                                flash_block_k=16, **kw))
    ld, bd = one_step(GPTConfig(**kw))
    np.testing.assert_allclose(lf, ld, rtol=2e-4)
    np.testing.assert_allclose(bf, bd, rtol=5e-3, atol=5e-4)
