"""The epoch-compiled (lax.scan) train step must match the per-step loop."""

import jax
import jax.numpy as jnp
import numpy as np

from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import (
    make_scanned_train_step,
    make_train_step,
)


def test_scanned_matches_per_step_loop():
    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od, n_microbatches=2)
    opt = sgd(0.1, 0.5)

    n_steps, batch = 4, 8
    xs = jax.random.normal(key, (n_steps, batch, 12))
    ts = jax.random.randint(key, (n_steps, batch), 0, 10)

    # scanned: one compiled program for all steps
    buf_a = pipe.init_params()
    st_a = opt.init(buf_a)
    scanned = make_scanned_train_step(pipe, opt)
    buf_a, st_a, losses = scanned(buf_a, st_a, xs, ts, key)

    # loop: same RNG schedule (fold_in(key, i))
    buf_b = pipe.init_params()
    st_b = opt.init(buf_b)
    step = make_train_step(pipe, opt)
    loop_losses = []
    for i in range(n_steps):
        buf_b, st_b, l = step(buf_b, st_b, xs[i], ts[i],
                              jax.random.fold_in(key, i))
        loop_losses.append(float(l))

    np.testing.assert_allclose(np.asarray(losses), loop_losses,
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(buf_a), np.asarray(buf_b),
                               rtol=2e-5, atol=2e-5)
