"""The epoch-compiled (lax.scan) train step must match the per-step loop."""

import jax
import jax.numpy as jnp
import numpy as np

from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import (
    make_scanned_train_step,
    make_train_step,
)


def test_scanned_matches_per_step_loop():
    key = jax.random.key(0)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od, n_microbatches=2)
    opt = sgd(0.1, 0.5)

    n_steps, batch = 4, 8
    xs = jax.random.normal(key, (n_steps, batch, 12))
    ts = jax.random.randint(key, (n_steps, batch), 0, 10)

    # scanned: one compiled program for all steps
    buf_a = pipe.init_params()
    st_a = opt.init(buf_a)
    scanned = make_scanned_train_step(pipe, opt)
    buf_a, st_a, losses = scanned(buf_a, st_a, xs, ts, key)

    # loop: same RNG schedule (fold_in(key, i))
    buf_b = pipe.init_params()
    st_b = opt.init(buf_b)
    step = make_train_step(pipe, opt)
    loop_losses = []
    for i in range(n_steps):
        buf_b, st_b, l = step(buf_b, st_b, xs[i], ts[i],
                              jax.random.fold_in(key, i))
        loop_losses.append(float(l))

    np.testing.assert_allclose(np.asarray(losses), loop_losses,
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(buf_a), np.asarray(buf_b),
                               rtol=2e-5, atol=2e-5)


def test_scanned_adamw_single_device_matches_loop():
    """Scalar-state optimizers (AdamW's step counter) must ride the
    single-device UNPACKED fast path and still match the per-step loop.

    Regression for the round-5 finding: the fast-path gate required every
    optimizer-state leaf to be buffer-shaped, so AdamW fell onto the packed
    engine (~1.9x bytes, ~7x live temp; benchmarks/opt_cost_analysis.py).
    """
    from simple_distributed_machine_learning_tpu.train.optimizer import adamw

    key = jax.random.key(3)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 1)
    mesh = make_mesh(n_stages=1, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od, n_microbatches=1)
    opt = adamw(5e-3)

    n_steps, batch = 4, 8
    xs = jax.random.normal(key, (n_steps, batch, 12))
    ts = jax.random.randint(key, (n_steps, batch), 0, 10)

    buf_a = pipe.init_params()
    st_a = opt.init(buf_a)
    scanned = make_scanned_train_step(pipe, opt)
    buf_a, st_a, losses = scanned(buf_a, st_a, xs, ts, key)

    buf_b = pipe.init_params()
    st_b = opt.init(buf_b)
    step = make_train_step(pipe, opt)
    loop_losses = []
    for i in range(n_steps):
        buf_b, st_b, l = step(buf_b, st_b, xs[i], ts[i],
                              jax.random.fold_in(key, i))
        loop_losses.append(float(l))

    np.testing.assert_allclose(np.asarray(losses), loop_losses,
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(buf_a), np.asarray(buf_b),
                               rtol=2e-5, atol=2e-5)
    # the step counter must come back as the scalar it went in as
    assert st_a[0].shape == ()
    assert int(st_a[0]) == n_steps


def test_adamw_rides_unpacked_fast_path():
    """Compiled-cost regression: on the trivial mesh, AdamW's scanned window
    must stay within ~1.6x of SGD's bytes accessed. The packed-engine
    fallback measured 1.9-2.0x (and 7x live temp) - if this ratio regresses,
    the fast-path gate broke again."""
    from simple_distributed_machine_learning_tpu.train.optimizer import adamw

    key = jax.random.key(4)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 1)
    mesh = make_mesh(n_stages=1, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od, n_microbatches=1)
    xs = jax.random.normal(key, (4, 8, 12))
    ts = jax.random.randint(key, (4, 8), 0, 10)

    def window_bytes(opt):
        buf = pipe.init_params()
        st = opt.init(buf)
        step = make_scanned_train_step(pipe, opt)
        compiled = step.lower(buf, st, xs, ts, key).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return cost["bytes accessed"]

    ratio = window_bytes(adamw(1e-3)) / window_bytes(sgd(0.1, 0.5))
    assert ratio < 1.6, f"AdamW window bytes {ratio:.2f}x SGD - packed-path?"

    # absolute anchor: a state shape the gate CANNOT unpack (a (2,)-vector
    # counter) forces the packed engine; the real AdamW must compile to
    # meaningfully less LIVE TEMP memory than that (bytes-accessed barely
    # separates at MLP scale, temp separates ~2x at [128,512,256,64]). If a
    # regression knocked every optimizer off the fast path, the adamw/sgd
    # ratio above would still pass (packed-vs-packed) but this anchor
    # catches it.
    from simple_distributed_machine_learning_tpu.train.optimizer import (
        Optimizer,
        adamw as _adamw,
    )

    def packed_adamw(lr) -> Optimizer:
        inner = _adamw(lr)

        def init(params):
            step, m, v = inner.init(params)
            return (jnp.zeros((2,), jnp.int32), m, v)

        def update(grads, state, params):
            vec, m, v = state
            new_params, (step, m, v) = inner.update(
                grads, (vec[0], m, v), params)
            return new_params, (jnp.stack([step, step]), m, v)

        return Optimizer(init, update)

    big, bwd, bod = make_mlp_stages(jax.random.key(5), [128, 512, 256, 64], 1)
    bpipe = Pipeline(big, make_mesh(n_stages=1, n_data=1), bwd, bod,
                     n_microbatches=1)
    bxs = jax.random.normal(key, (8, 16, 128))
    bts = jax.random.randint(key, (8, 16), 0, 64)

    def window_temp(opt):
        buf = bpipe.init_params()
        st = opt.init(buf)
        step = make_scanned_train_step(bpipe, opt)
        compiled = step.lower(buf, st, bxs, bts, key).compile()
        return compiled.memory_analysis().temp_size_in_bytes

    temp_ratio = window_temp(adamw(1e-3)) / window_temp(packed_adamw(1e-3))
    assert temp_ratio < 0.7, (
        f"AdamW live temp {temp_ratio:.2f}x the forced-packed engine - "
        f"did the fast-path gate regress for every optimizer?")


def test_scanned_clip_single_device_matches_loop():
    """clip_by_global_norm(adamw, ..., replication_weights()) on the trivial
    mesh: the scanned fast path unpacks grads to per-param pytrees, so the
    packed-buffer norm_weights no longer align leaf-for-leaf. Regression for
    the silent zip-truncation that computed the global norm from the FIRST
    gradient leaf only (under-clipping); the wrapper must detect the
    identity-weight case, drop the weights, and match the per-step packed
    loop exactly — with max_norm small enough that clipping is ACTIVE."""
    from simple_distributed_machine_learning_tpu.train.optimizer import (
        adamw,
        clip_by_global_norm,
    )

    key = jax.random.key(7)
    stages, wd, od = make_mlp_stages(key, [12, 16, 10], 1)
    mesh = make_mesh(n_stages=1, n_data=1)
    pipe = Pipeline(stages, mesh, wd, od, n_microbatches=1)
    # max_norm far below a fresh-init nll gradient's global norm: every step
    # clips, so a wrong norm changes the trajectory
    opt = clip_by_global_norm(adamw(5e-3), 1e-3, pipe.replication_weights())

    n_steps, batch = 4, 8
    xs = jax.random.normal(key, (n_steps, batch, 12))
    ts = jax.random.randint(key, (n_steps, batch), 0, 10)

    buf_a = pipe.init_params()
    st_a = opt.init(buf_a)
    scanned = make_scanned_train_step(pipe, opt)
    buf_a, st_a, losses = scanned(buf_a, st_a, xs, ts, key)

    buf_b = pipe.init_params()
    st_b = opt.init(buf_b)
    step = make_train_step(pipe, opt)     # packed path: weights align
    loop_losses = []
    for i in range(n_steps):
        buf_b, st_b, l = step(buf_b, st_b, xs[i], ts[i],
                              jax.random.fold_in(key, i))
        loop_losses.append(float(l))

    np.testing.assert_allclose(np.asarray(losses), loop_losses,
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(buf_a), np.asarray(buf_b),
                               rtol=2e-5, atol=2e-5)

    # non-identity weights CANNOT be mapped onto unpacked grads — loud error,
    # not a silently wrong norm
    import pytest

    bad = clip_by_global_norm(adamw(5e-3), 1e-3,
                              0.5 * pipe.replication_weights())
    buf_c = pipe.init_params()
    st_c = bad.init(buf_c)
    with pytest.raises(ValueError, match="non-identity"):
        make_scanned_train_step(pipe, bad)(buf_c, st_c, xs, ts, key)
