"""Test config: run everything on 8 virtual CPU devices.

This is the fake-cluster mechanism the reference lacks entirely (it has no
tests; its only validation is launching two real processes, SURVEY §4):
``--xla_force_host_platform_device_count=8`` gives one process 8 XLA devices,
so every pipeline/ppermute/shard_map path — including multi-stage meshes with
data parallelism — runs hermetically without a TPU.

Must run before jax initializes its backends, hence module scope in conftest.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the ambient env pins the TPU plugin
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The container's sitecustomize imports jax at interpreter startup (to register
# the TPU plugin), which latches JAX_PLATFORMS before this file runs — so also
# force the platform through the live config.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# version-tolerant: jax_num_cpu_devices where it exists, the XLA_FLAGS route
# (set above) everywhere else
from simple_distributed_machine_learning_tpu.parallel.compat import (  # noqa: E402
    set_host_device_count,
)

set_host_device_count(8)
