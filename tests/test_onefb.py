"""1F1B schedule: loss/grad parity with the GPipe engine and the fused model.

The two engines compute the SAME objective by construction; these tests pin
it numerically across topologies, microbatch counts, weighted batches and
aux-loss (dense-MoE) stages — the same bar the GPipe engine met
(tests/test_pipeline.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline


def _pipes(dims, n_stages, n_data=1, n_micro=1):
    key = jax.random.key(0)
    stages, wire, out = make_mlp_stages(key, dims, n_stages)
    mesh = make_mesh(n_stages=n_stages, n_data=n_data)
    gp = Pipeline(stages, mesh, wire, out, n_microbatches=n_micro)
    fb = Pipeline(stages, mesh, wire, out, n_microbatches=n_micro,
                  schedule="1f1b")
    return gp, fb


def _data(dims, batch, seed=1):
    x = jax.random.normal(jax.random.key(seed), (batch, dims[0]))
    y = jax.random.randint(jax.random.key(seed + 1), (batch,), 0, dims[-1])
    return x, y


@pytest.mark.parametrize("n_stages,n_data,n_micro,batch", [
    (2, 1, 1, 8),     # the reference's sequential schedule
    (2, 1, 4, 8),     # GPipe microbatching
    (4, 1, 4, 8),     # deeper pipeline
    (2, 2, 2, 8),     # dp x pp
    (4, 2, 4, 16),    # dp x deep pp
])
def test_1f1b_matches_gpipe_loss_and_grads(n_stages, n_data, n_micro, batch):
    dims = [12, 16, 16, 16, 10][: n_stages + 1] if n_stages > 2 else [12, 16, 10]
    gp, fb = _pipes(dims, n_stages, n_data, n_micro)
    x, y = _data(dims, batch)
    buf = gp.init_params()
    key = jax.random.key(7)
    lg, gg = gp.loss_and_grads(buf, x, y, key, deterministic=True)
    lf, gf = fb.loss_and_grads(buf, x, y, key, deterministic=True)
    np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(gf),
                               rtol=2e-4, atol=1e-6)


def test_1f1b_weighted_batch_matches():
    """Ragged-batch 0/1 weights flow through the manual backward seeds."""
    gp, fb = _pipes([12, 16, 10], 2, n_micro=2)
    x, y = _data([12, 16, 10], 8)
    w = jnp.asarray([1, 1, 1, 1, 1, 0, 0, 0], jnp.float32)
    buf = gp.init_params()
    key = jax.random.key(3)
    lg, gg = gp.loss_and_grads(buf, x, y, key, deterministic=True, weights=w)
    lf, gf = fb.loss_and_grads(buf, x, y, key, deterministic=True, weights=w)
    np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(gf),
                               rtol=2e-4, atol=1e-6)


def test_1f1b_sgd_trajectory_matches_gpipe():
    from simple_distributed_machine_learning_tpu.train.optimizer import sgd
    from simple_distributed_machine_learning_tpu.train.step import (
        make_train_step,
    )

    gp, fb = _pipes([12, 16, 10], 2, n_micro=2)
    x, y = _data([12, 16, 10], 8)
    opt = sgd(0.1, 0.5)
    res = {}
    for name, pipe in (("gpipe", gp), ("1f1b", fb)):
        buf = pipe.init_params()
        state = opt.init(buf)
        step = make_train_step(pipe, opt)
        for i in range(4):
            # deterministic parity needs dropout-free stages; the MLP has
            # none, so the differing RNG streams do not matter
            buf, state, loss = step(buf, state, x, y,
                                    jax.random.fold_in(jax.random.key(0), i))
        res[name] = (np.asarray(buf), float(loss))
    np.testing.assert_allclose(res["gpipe"][0], res["1f1b"][0],
                               rtol=3e-4, atol=1e-6)
    np.testing.assert_allclose(res["gpipe"][1], res["1f1b"][1], rtol=1e-4)


def test_1f1b_moe_aux_stage_matches():
    """Dense-MoE stages return (y, aux): the aux seed (1/(M*n_data)) must
    reproduce the GPipe engine's unweighted aux mean exactly."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )

    cfg = GPTConfig(vocab=32, seq_len=8, d_model=16, n_heads=2, n_layers=2,
                    n_experts=2, moe_top_k=1)
    key = jax.random.key(0)
    stages, wire, out = make_gpt_stages(key, cfg, 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    gp = Pipeline(stages, mesh, wire, out, n_microbatches=2)
    fb = Pipeline(stages, mesh, wire, out, n_microbatches=2, schedule="1f1b")
    x = jax.random.randint(jax.random.key(1), (4, cfg.seq_len), 0,
                           cfg.vocab).astype(jnp.float32)
    y = jax.random.randint(jax.random.key(2), (4, cfg.seq_len), 0, cfg.vocab)
    buf = gp.init_params()
    lg, gg = gp.loss_and_grads(buf, x, y, key, deterministic=True)
    lf, gf = fb.loss_and_grads(buf, x, y, key, deterministic=True)
    np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(gf),
                               rtol=5e-4, atol=2e-6)


def test_1f1b_rejects_sharded_meshes():
    from simple_distributed_machine_learning_tpu.parallel.onefb import (
        build_1f1b_fn,
    )
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        make_mlp_tp_stages,
    )

    stages, wire, out = make_mlp_tp_stages(jax.random.key(0),
                                           [8, 16, 16, 16, 4], 2, 2)
    mesh = make_mesh(n_stages=2, n_model=2)
    pipe = Pipeline(stages, mesh, wire, out, schedule="1f1b")
    with pytest.raises(ValueError, match="stage\\+data meshes only"):
        build_1f1b_fn(pipe, True)


def test_1f1b_memory_flat_in_microbatches():
    """The schedule's reason to exist: compiled temp memory is bounded by
    the topology S, not the microbatch count M (GPipe's grows with M
    because autodiff keeps every microbatch's residuals alive between the
    sweeps). Measured from XLA's own memory analysis."""

    def temp_bytes(schedule, M):
        stages, wire, out = make_mlp_stages(jax.random.key(0),
                                            [256, 256, 10], 2)
        mesh = make_mesh(n_stages=2, n_data=1)
        p = Pipeline(stages, mesh, wire, out, n_microbatches=M,
                     schedule=schedule)
        x = jax.random.normal(jax.random.key(1), (16 * M, 256))
        y = jax.random.randint(jax.random.key(2), (16 * M,), 0, 10)
        buf = p.init_params()
        f = jax.jit(lambda b: p.loss_and_grads(b, x, y, jax.random.key(3),
                                               deterministic=True))
        return f.lower(buf).compile().memory_analysis().temp_size_in_bytes

    g4, g32 = temp_bytes("gpipe", 4), temp_bytes("gpipe", 32)
    f4, f32 = temp_bytes("1f1b", 4), temp_bytes("1f1b", 32)
    assert g32 / g4 > 2.0, (g4, g32)       # GPipe residuals scale with M
    assert f32 / f4 < 1.3, (f4, f32)       # 1F1B stays topology-bounded


def test_cli_1f1b_end_to_end(capsys):
    from simple_distributed_machine_learning_tpu.cli import main

    main(["--rank", "0", "--world_size", "1", "--model", "mlp",
          "--mlp-dims", "784,32,10", "--stages", "2", "--epochs", "1",
          "--data-root", "/nonexistent", "--microbatches", "4",
          "--schedule", "1f1b"])
    out = capsys.readouterr().out
    assert "Test set: Average loss:" in out


def test_cli_1f1b_rejects_tp():
    import pytest as _pytest

    from simple_distributed_machine_learning_tpu.cli import main

    with _pytest.raises(SystemExit, match="stage\\+data meshes only"):
        main(["--rank", "0", "--model", "mlp", "--schedule", "1f1b",
              "--tp", "2"])


def test_cli_1f1b_gpt(capsys):
    """GPT family under the 1F1B schedule through the CLI (per-token LM
    loss, dropout active, embedding/head stages vjp-recomputed)."""
    from simple_distributed_machine_learning_tpu.cli import main

    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--stages", "2", "--epochs", "1", "--microbatches", "2",
          "--batch-size", "32", "--lr", "0.01",
          "--schedule", "1f1b"])
    out = capsys.readouterr().out
    assert "Test set: Average loss:" in out


@pytest.mark.parametrize("attn", ["ring", "ulysses"])
def test_1f1b_seq_parallel_matches_gpipe(attn):
    """1F1B x sequence parallelism: token axis sharded over the seq axis,
    ring/Ulysses collectives inside the vjp-recomputed stages. Loss and
    packed-buffer grads must match the GPipe engine on the same sp mesh.

    Runs in a SUBPROCESS: stacking several 4-device seq-collective programs
    in one process can trip XLA:CPU's InProcessCommunicator rendezvous
    timeout on a loaded single-core machine (observed 'only 2 of 4 arrived'
    aborts); each config is timing-clean in a fresh interpreter."""
    import os
    import subprocess
    import sys

    code = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
from simple_distributed_machine_learning_tpu.models.gpt import GPTConfig, make_gpt_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import make_train_step

cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=4, n_layers=2,
                attn_impl={attn!r}, n_seq=2)
stages, wd, od = make_gpt_stages(jax.random.key(0), cfg, 2)
mesh = make_mesh(n_stages=2, n_data=1, n_seq=2)
gp = Pipeline(stages, mesh, wd, od, n_microbatches=2)
fb = Pipeline(stages, mesh, wd, od, n_microbatches=2, schedule="1f1b")
x = jax.random.randint(jax.random.key(1), (4, cfg.seq_len), 0,
                       cfg.vocab).astype(jnp.float32)
y = jax.random.randint(jax.random.key(2), (4, cfg.seq_len), 0, cfg.vocab)
buf = gp.init_params()
key = jax.random.key(7)
lg, gg = gp.loss_and_grads(buf, x, y, key, deterministic=True)
lf, gf = fb.loss_and_grads(buf, x, y, key, deterministic=True)
np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)
np.testing.assert_allclose(np.asarray(gg), np.asarray(gf),
                           rtol=5e-4, atol=2e-6)
# and a pp x dp x sp train step: loss falls
mesh2 = make_mesh(n_stages=2, n_data=2, n_seq=2)
pipe = Pipeline(stages, mesh2, wd, od, n_microbatches=2, schedule="1f1b")
buf2 = pipe.init_params()
opt = sgd(0.1, 0.5)
state = opt.init(buf2)
step = make_train_step(pipe, opt)
x8 = jax.random.randint(jax.random.key(1), (8, cfg.seq_len), 0,
                        cfg.vocab).astype(jnp.float32)
y8 = jax.random.randint(jax.random.key(2), (8, cfg.seq_len), 0, cfg.vocab)
losses = []
for i in range(4):
    buf2, state, loss = step(buf2, state, x8, y8,
                             jax.random.fold_in(jax.random.key(3), i))
    losses.append(float(loss))
assert losses[-1] < losses[0], losses
print("SEQ_1F1B_OK", losses[-1])
"""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # retry on XLA:CPU's InProcessCommunicator rendezvous-timeout abort: on a
    # single-core machine the 4 device threads can starve each other past
    # the hard 40 s rendezvous deadline (thread-scheduling luck, not a
    # program-order divergence — see module docstring); the parity asserts
    # inside the script are what this test is for
    last = None
    for _ in range(3):
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=560, cwd=repo, env=env)
        last = r
        if r.returncode == 0 or "Termination timeout" not in r.stderr:
            break
    if last.returncode != 0 and "Termination timeout" in last.stderr:
        # every attempt died in the rendezvous, not in a numeric assert:
        # record the runtime artifact without failing CI (ulysses — whose
        # collective mix does not trip it — remains the hard gate)
        pytest.skip(f"XLA:CPU in-process rendezvous starvation ({attn})")
    assert last.returncode == 0, f"seq-1f1b {attn} failed:\n{last.stderr[-3000:]}"
    assert "SEQ_1F1B_OK" in last.stdout
