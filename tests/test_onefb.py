"""1F1B schedule: loss/grad parity with the GPipe engine and the fused model.

The two engines compute the SAME objective by construction; these tests pin
it numerically across topologies, microbatch counts, weighted batches and
aux-loss (dense-MoE) stages — the same bar the GPipe engine met
(tests/test_pipeline.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline



# sweep-heavy module: slow-tier (per-round gate). The quick per-commit gate
# still exercises the 1F1B engine via the parity smoke in
# tests/test_schedules.py::test_1f1b_quick_parity_smoke.
pytestmark = pytest.mark.slow


def _pipes(dims, n_stages, n_data=1, n_micro=1):
    key = jax.random.key(0)
    stages, wire, out = make_mlp_stages(key, dims, n_stages)
    mesh = make_mesh(n_stages=n_stages, n_data=n_data)
    gp = Pipeline(stages, mesh, wire, out, n_microbatches=n_micro)
    fb = Pipeline(stages, mesh, wire, out, n_microbatches=n_micro,
                  schedule="1f1b")
    return gp, fb


def _data(dims, batch, seed=1):
    x = jax.random.normal(jax.random.key(seed), (batch, dims[0]))
    y = jax.random.randint(jax.random.key(seed + 1), (batch,), 0, dims[-1])
    return x, y


@pytest.mark.parametrize("n_stages,n_data,n_micro,batch", [
    (2, 1, 1, 8),     # the reference's sequential schedule
    (2, 1, 4, 8),     # GPipe microbatching
    (4, 1, 4, 8),     # deeper pipeline
    (2, 2, 2, 8),     # dp x pp
    (4, 2, 4, 16),    # dp x deep pp
])
def test_1f1b_matches_gpipe_loss_and_grads(n_stages, n_data, n_micro, batch):
    dims = [12, 16, 16, 16, 10][: n_stages + 1] if n_stages > 2 else [12, 16, 10]
    gp, fb = _pipes(dims, n_stages, n_data, n_micro)
    x, y = _data(dims, batch)
    buf = gp.init_params()
    key = jax.random.key(7)
    lg, gg = gp.loss_and_grads(buf, x, y, key, deterministic=True)
    lf, gf = fb.loss_and_grads(buf, x, y, key, deterministic=True)
    np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(gf),
                               rtol=2e-4, atol=1e-6)


def test_1f1b_weighted_batch_matches():
    """Ragged-batch 0/1 weights flow through the manual backward seeds."""
    gp, fb = _pipes([12, 16, 10], 2, n_micro=2)
    x, y = _data([12, 16, 10], 8)
    w = jnp.asarray([1, 1, 1, 1, 1, 0, 0, 0], jnp.float32)
    buf = gp.init_params()
    key = jax.random.key(3)
    lg, gg = gp.loss_and_grads(buf, x, y, key, deterministic=True, weights=w)
    lf, gf = fb.loss_and_grads(buf, x, y, key, deterministic=True, weights=w)
    np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(gf),
                               rtol=2e-4, atol=1e-6)


def test_1f1b_sgd_trajectory_matches_gpipe():
    from simple_distributed_machine_learning_tpu.train.optimizer import sgd
    from simple_distributed_machine_learning_tpu.train.step import (
        make_train_step,
    )

    gp, fb = _pipes([12, 16, 10], 2, n_micro=2)
    x, y = _data([12, 16, 10], 8)
    opt = sgd(0.1, 0.5)
    res = {}
    for name, pipe in (("gpipe", gp), ("1f1b", fb)):
        buf = pipe.init_params()
        state = opt.init(buf)
        step = make_train_step(pipe, opt)
        for i in range(4):
            # deterministic parity needs dropout-free stages; the MLP has
            # none, so the differing RNG streams do not matter
            buf, state, loss = step(buf, state, x, y,
                                    jax.random.fold_in(jax.random.key(0), i))
        res[name] = (np.asarray(buf), float(loss))
    np.testing.assert_allclose(res["gpipe"][0], res["1f1b"][0],
                               rtol=3e-4, atol=1e-6)
    np.testing.assert_allclose(res["gpipe"][1], res["1f1b"][1], rtol=1e-4)


def test_1f1b_moe_aux_stage_matches():
    """Dense-MoE stages return (y, aux): the aux seed (1/(M*n_data)) must
    reproduce the GPipe engine's unweighted aux mean exactly."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )

    cfg = GPTConfig(vocab=32, seq_len=8, d_model=16, n_heads=2, n_layers=2,
                    n_experts=2, moe_top_k=1)
    key = jax.random.key(0)
    stages, wire, out = make_gpt_stages(key, cfg, 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    gp = Pipeline(stages, mesh, wire, out, n_microbatches=2)
    fb = Pipeline(stages, mesh, wire, out, n_microbatches=2, schedule="1f1b")
    x = jax.random.randint(jax.random.key(1), (4, cfg.seq_len), 0,
                           cfg.vocab).astype(jnp.float32)
    y = jax.random.randint(jax.random.key(2), (4, cfg.seq_len), 0, cfg.vocab)
    buf = gp.init_params()
    lg, gg = gp.loss_and_grads(buf, x, y, key, deterministic=True)
    lf, gf = fb.loss_and_grads(buf, x, y, key, deterministic=True)
    np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(gf),
                               rtol=5e-4, atol=2e-6)


@pytest.mark.parametrize("n_experts,top_k,n_data", [(2, 2, 1), (4, 2, 2)])
def test_1f1b_expert_parallel_matches_gpipe(n_experts, top_k, n_data):
    """1F1B x expert parallelism: EP-sharded MoE stages (2x all-to-all
    dispatch, grad-synced replicated leaves, nonzero aux weight) on an
    expert=2 mesh match the GPipe engine. The aux path is the crux: each
    stage's expert-invariant aux is pcast to varying inside the
    differentiated function so its transpose reassembles the full aux
    cotangent from the per-slot 1/n seeds (see onefb.py docstring)."""
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )

    cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=2, n_layers=2,
                    n_experts=n_experts, moe_top_k=top_k,
                    n_expert_parallel=2, moe_aux_weight=0.01)
    stages, wd, od = make_gpt_stages(jax.random.key(0), cfg, 2)
    mesh = make_mesh(n_stages=2, n_data=n_data, n_expert=2)
    gp = Pipeline(stages, mesh, wd, od, n_microbatches=2)
    fb = Pipeline(stages, mesh, wd, od, n_microbatches=2, schedule="1f1b")
    x = jax.random.randint(jax.random.key(1), (8, cfg.seq_len), 0,
                           cfg.vocab).astype(jnp.float32)
    y = jax.random.randint(jax.random.key(2), (8, cfg.seq_len), 0, cfg.vocab)
    buf = gp.init_params()
    k = jax.random.key(7)
    lg, gg = gp.loss_and_grads(buf, x, y, k, deterministic=True)
    lf, gf = fb.loss_and_grads(buf, x, y, k, deterministic=True)
    np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(gf),
                               rtol=5e-4, atol=2e-6)


def test_1f1b_memory_flat_in_microbatches():
    """The schedule's reason to exist: compiled temp memory is bounded by
    the topology S, not the microbatch count M (GPipe's grows with M
    because autodiff keeps every microbatch's residuals alive between the
    sweeps). Measured from XLA's own memory analysis, via the SAME helper
    benchmarks/onefb_memory.py records its artifact with."""
    import importlib.util
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "onefb_memory", os.path.join(repo, "benchmarks", "onefb_memory.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    temp_bytes = mod.temp_bytes

    g4, g32 = temp_bytes("gpipe", 4), temp_bytes("gpipe", 32)
    f4, f32 = temp_bytes("1f1b", 4), temp_bytes("1f1b", 32)
    assert g32 / g4 > 2.0, (g4, g32)       # GPipe residuals scale with M
    assert f32 / f4 < 1.3, (f4, f32)       # 1F1B stays topology-bounded


def test_cli_1f1b_end_to_end(capsys):
    from simple_distributed_machine_learning_tpu.cli import main

    main(["--rank", "0", "--world_size", "1", "--model", "mlp",
          "--mlp-dims", "784,32,10", "--stages", "2", "--epochs", "1",
          "--data-root", "/nonexistent", "--microbatches", "4",
          "--schedule", "1f1b"])
    out = capsys.readouterr().out
    assert "Test set: Average loss:" in out


def test_cli_1f1b_ep_end_to_end(capsys):
    from simple_distributed_machine_learning_tpu.cli import main

    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--stages", "2", "--epochs", "1", "--microbatches", "2",
          "--batch-size", "32", "--lr", "0.01", "--experts", "2",
          "--ep", "2", "--schedule", "1f1b"])
    out = capsys.readouterr().out
    assert "Test set: Average loss:" in out


def test_cli_1f1b_gpt(capsys):
    """GPT family under the 1F1B schedule through the CLI (per-token LM
    loss, dropout active, embedding/head stages vjp-recomputed)."""
    from simple_distributed_machine_learning_tpu.cli import main

    main(["--rank", "0", "--world_size", "1", "--model", "gpt",
          "--stages", "2", "--epochs", "1", "--microbatches", "2",
          "--batch-size", "32", "--lr", "0.01",
          "--schedule", "1f1b"])
    out = capsys.readouterr().out
    assert "Test set: Average loss:" in out


@pytest.mark.parametrize("attn", ["ring", "ulysses"])
def test_1f1b_seq_parallel_matches_gpipe(attn):
    """1F1B x sequence parallelism: token axis sharded over the seq axis,
    ring/Ulysses collectives inside the vjp-recomputed stages. Loss and
    packed-buffer grads must match the GPipe engine on the same sp mesh.

    Runs in a SUBPROCESS: stacking several 4-device seq-collective programs
    in one process can trip XLA:CPU's InProcessCommunicator rendezvous
    timeout on a loaded single-core machine (observed 'only 2 of 4 arrived'
    aborts); each config is timing-clean in a fresh interpreter."""
    import os
    import subprocess
    import sys

    from simple_distributed_machine_learning_tpu.parallel.compat import (
        HAS_VMA,
    )

    if attn == "ring" and not HAS_VMA:
        # ring attention's ppermutes live inside the stage switch: on old
        # jax's XLA:CPU the global collective-permute rendezvous deadlocks
        # under the branch-skewed execution (the documented CPU caveat —
        # statically flagged by analysis/ as ppermute-deadlock.ring-in-branch
        # and pinned by tests/test_analysis.py); the subprocess would hang
        # to its timeout. Ulysses (all_to_all) remains the old-jax gate.
        pytest.skip("old jax: branch-divergent ppermute rings deadlock "
                    "XLA:CPU (analysis/ flags this shape statically)")

    code = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax, jax.numpy as jnp, numpy as np
jax.config.update("jax_platforms", "cpu")
# version-tolerant 8-virtual-device setup: jax_num_cpu_devices where it
# exists, the XLA_FLAGS route (set above) everywhere else — same shim as
# tests/conftest.py (a bare config.update AttributeErrors on old jax)
from simple_distributed_machine_learning_tpu.parallel.compat import set_host_device_count
set_host_device_count(8)
from simple_distributed_machine_learning_tpu.models.gpt import GPTConfig, make_gpt_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.optimizer import sgd
from simple_distributed_machine_learning_tpu.train.step import make_train_step

cfg = GPTConfig(vocab=32, seq_len=16, d_model=32, n_heads=4, n_layers=2,
                attn_impl={attn!r}, n_seq=2)
stages, wd, od = make_gpt_stages(jax.random.key(0), cfg, 2)
mesh = make_mesh(n_stages=2, n_data=1, n_seq=2)
gp = Pipeline(stages, mesh, wd, od, n_microbatches=2)
fb = Pipeline(stages, mesh, wd, od, n_microbatches=2, schedule="1f1b")
x = jax.random.randint(jax.random.key(1), (4, cfg.seq_len), 0,
                       cfg.vocab).astype(jnp.float32)
y = jax.random.randint(jax.random.key(2), (4, cfg.seq_len), 0, cfg.vocab)
buf = gp.init_params()
key = jax.random.key(7)
lg, gg = gp.loss_and_grads(buf, x, y, key, deterministic=True)
lf, gf = fb.loss_and_grads(buf, x, y, key, deterministic=True)
np.testing.assert_allclose(float(lg), float(lf), rtol=1e-5)
np.testing.assert_allclose(np.asarray(gg), np.asarray(gf),
                           rtol=5e-4, atol=2e-6)
# and a pp x dp x sp train step: loss falls
mesh2 = make_mesh(n_stages=2, n_data=2, n_seq=2)
pipe = Pipeline(stages, mesh2, wd, od, n_microbatches=2, schedule="1f1b")
buf2 = pipe.init_params()
opt = sgd(0.1, 0.5)
state = opt.init(buf2)
step = make_train_step(pipe, opt)
x8 = jax.random.randint(jax.random.key(1), (8, cfg.seq_len), 0,
                        cfg.vocab).astype(jnp.float32)
y8 = jax.random.randint(jax.random.key(2), (8, cfg.seq_len), 0, cfg.vocab)
losses = []
for i in range(4):
    buf2, state, loss = step(buf2, state, x8, y8,
                             jax.random.fold_in(jax.random.key(3), i))
    losses.append(float(loss))
assert losses[-1] < losses[0], losses
print("SEQ_1F1B_OK", losses[-1])
"""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # retry on XLA:CPU's InProcessCommunicator rendezvous-timeout abort: on a
    # single-core machine the 4 device threads can starve each other past
    # the hard 40 s rendezvous deadline (thread-scheduling luck, not a
    # program-order divergence — see module docstring); the parity asserts
    # inside the script are what this test is for
    last = None
    for _ in range(3):
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=560, cwd=repo, env=env)
        last = r
        if r.returncode == 0 or "Termination timeout" not in r.stderr:
            break
    if last.returncode != 0 and "Termination timeout" in last.stderr:
        # every attempt died in the rendezvous, not in a numeric assert:
        # record the runtime artifact without failing CI (ulysses — whose
        # collective mix does not trip it — remains the hard gate)
        pytest.skip(f"XLA:CPU in-process rendezvous starvation ({attn})")
    assert last.returncode == 0, f"seq-1f1b {attn} failed:\n{last.stderr[-3000:]}"
    assert "SEQ_1F1B_OK" in last.stdout


def test_1f1b_tensor_parallel_matches_gpipe():
    """1F1B x tensor parallelism: Megatron column->row stages on a
    dp x pp x tp mesh. The wire is typed model-invariant so the pullback's
    implicit psum assembles per-shard partial cotangents; grads must be
    BIT-EXACT vs the GPipe engine (same collectives, same order)."""
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        make_mlp_tp_stages,
    )

    stages, wd, od = make_mlp_tp_stages(jax.random.key(0),
                                        [8, 16, 16, 16, 4], 2, 2)
    x = jax.random.normal(jax.random.key(1), (8, 8))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 4)
    for nd in (1, 2):
        mesh = make_mesh(n_stages=2, n_model=2, n_data=nd)
        gp = Pipeline(stages, mesh, wd, od, n_microbatches=2)
        fb = Pipeline(stages, mesh, wd, od, n_microbatches=2,
                      schedule="1f1b")
        buf = gp.init_params()
        k = jax.random.key(7)
        lg, gg = gp.loss_and_grads(buf, x, y, k, deterministic=True)
        lf, gf = fb.loss_and_grads(buf, x, y, k, deterministic=True)
        np.testing.assert_allclose(float(lg), float(lf), rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(gg), np.asarray(gf))


def test_1f1b_replicated_stages_on_tp_mesh_match_fused():
    """Plain (unsharded) stages on a model=2 mesh compute redundantly per
    slot; the rescaled pullback must give every slot the FULL gradient
    (slot grads identical and equal to the fused single-device grads).
    (Historically the GPipe engine's switch transpose rejected this case;
    its branch anchor now covers it too — tests/test_pipeline.py.)"""
    from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        fused_reference,
    )
    from simple_distributed_machine_learning_tpu.parallel.staging import (
        unpack_stage_params,
    )

    stages, wd, od = make_mlp_stages(jax.random.key(0), [8, 16, 4], 2)
    mesh = make_mesh(n_stages=2, n_model=2, n_data=1)
    fb = Pipeline(stages, mesh, wd, od, n_microbatches=2, schedule="1f1b")
    x = jax.random.normal(jax.random.key(1), (8, 8))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 4)
    buf = fb.init_params()
    k = jax.random.key(7)
    fused = fused_reference(stages)

    def floss(b):
        ps = [unpack_stage_params(b[s, 0, 0], fb.metas[s]) for s in range(2)]
        return nll_loss(fused(ps, x, k, True), y, "mean")

    lF, gF = jax.value_and_grad(floss)(buf)
    lf, gf = fb.loss_and_grads(buf, x, y, k, deterministic=True)
    np.testing.assert_allclose(float(lF), float(lf), rtol=1e-6)
    gF, gf = np.asarray(gF), np.asarray(gf)
    for s in range(2):
        # every model slot holds the full gradient (the fused reference
        # only populated slot 0)
        for m in range(2):
            np.testing.assert_allclose(gf[s, m, 0], gF[s, 0, 0],
                                       rtol=1e-5, atol=1e-7)


def test_1f1b_mixed_tp_and_plain_stages_grad_check():
    """A TP pair stage feeding plain stages on one model=2 mesh: loss and
    every gradient leaf match a hand-fused single-device reference
    (GPipe's backward cannot run this stage mix — its switch transpose
    trips a vma mismatch — so the fused model is the ground truth).

    Replicated leaves INSIDE the sharded stage (the row bias, kept in sync
    by grad_sync) get the FULL cotangent on every slot, so they are
    compared against a reference that differentiates ONE shared copy."""
    from simple_distributed_machine_learning_tpu.ops.losses import nll_loss
    from simple_distributed_machine_learning_tpu.parallel.staging import (
        unpack_stage_params,
    )
    from simple_distributed_machine_learning_tpu.parallel.tensor import (
        make_mlp_tp_stages,
    )

    tps, twd, _ = make_mlp_tp_stages(jax.random.key(0),
                                     [8, 16, 16, 16, 4], 2, 2)
    ps, pwd, pod = make_mlp_stages(jax.random.key(3), [16, 12, 4], 2)
    mixed = [tps[0], ps[0], ps[1]]
    mesh = make_mesh(n_stages=3, n_model=2, n_data=1)
    gp = Pipeline(mixed, mesh, max(twd, pwd), pod, n_microbatches=2)
    fb = Pipeline(mixed, mesh, max(twd, pwd), pod, n_microbatches=2,
                  schedule="1f1b")
    x = jax.random.normal(jax.random.key(1), (8, 8))
    y = jax.random.randint(jax.random.key(2), (8,), 0, 4)
    buf = fb.init_params()
    k = jax.random.key(7)
    lg = gp.loss(buf, x, y, k, deterministic=True)   # fwd engines agree
    lf, gf = fb.loss_and_grads(buf, x, y, k, deterministic=True)
    np.testing.assert_allclose(float(lg), float(lf), rtol=1e-6)

    def floss(b):
        sh = [unpack_stage_params(b[0, m, 0], fb.metas[0]) for m in range(2)]
        acc = 0
        for m in range(2):
            p = sh[m]
            hm = jnp.maximum(x @ p["w1"]["w"] + p["w1"]["b"], 0)
            acc = acc + hm @ p["w2"]["w"]
        # ONE shared bias copy (slot 0): its gradient is the full cotangent
        h = jnp.maximum(acc + sh[0]["w2"]["b"], 0)
        for s in (1, 2):
            p = unpack_stage_params(b[s, 0, 0], fb.metas[s])
            h = fb.stages[s].apply(p, h.reshape(h.shape[0], -1), k, True)
        return nll_loss(h, y, "mean")

    lF, gF = jax.value_and_grad(floss)(buf)
    np.testing.assert_allclose(float(lF), float(lf), rtol=1e-6)
    gF, gfn = np.asarray(gF), np.asarray(gf)
    meta0 = fb.metas[0]
    ref0 = unpack_stage_params(jnp.asarray(gF[0, 0, 0]), meta0)
    for m in range(2):
        got = unpack_stage_params(jnp.asarray(gfn[0, m, 0]), meta0)
        ref_m = unpack_stage_params(jnp.asarray(gF[0, m, 0]), meta0)
        # sharded leaves: per-slot reference; the replicated bias: the
        # shared-copy (slot 0) reference on every slot
        np.testing.assert_allclose(got["w1"]["w"], ref_m["w1"]["w"],
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(got["w1"]["b"], ref_m["w1"]["b"],
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(got["w2"]["w"], ref_m["w2"]["w"],
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(got["w2"]["b"], ref0["w2"]["b"],
                                   rtol=1e-5, atol=1e-7)
    for s in (1, 2):
        for m in range(2):
            np.testing.assert_allclose(gfn[s, m, 0], gF[s, 0, 0],
                                       rtol=1e-5, atol=1e-7)
