"""telemetry/: registry semantics, StepTimer compile-vs-steady split,
Chrome-trace well-formedness, bubble model, the static ICI gauge, the
hardened profiler.trace, and a Trainer smoke run with a full session."""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu import telemetry as tm
from simple_distributed_machine_learning_tpu.data.mnist import (
    Dataset,
    synthetic_mnist,
)
from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.trainer import (
    TrainConfig,
    Trainer,
)


# -- registry -------------------------------------------------------------

def test_counter_is_monotonic():
    reg = tm.MetricsRegistry()
    c = reg.counter("steps")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError, match="monotonic"):
        c.inc(-1)
    assert c.value == 5
    # same (name, labels) -> the same live instrument, not a fork
    assert reg.counter("steps") is c


def test_gauge_and_snapshot_roundtrip():
    reg = tm.MetricsRegistry()
    reg.gauge("loss").set(1.5)
    reg.counter("n", labels={"stage": "0"}).inc(3)
    snap = reg.snapshot()
    assert snap["loss"] == 1.5
    assert snap["n{stage=0}"] == 3
    json.loads(json.dumps(snap))            # JSON-serializable as claimed


def test_histogram_quantiles_nearest_rank():
    h = tm.Histogram("lat")
    for v in range(1, 101):
        h.observe(float(v))
    assert h.count == 100
    assert h.quantile(0.5) == 50.0
    assert h.quantile(0.95) == 95.0
    assert h.quantile(0.0) == 1.0 and h.quantile(1.0) == 100.0
    assert h.max == 100.0
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_histogram_weighted_observations():
    h = tm.Histogram("lat")
    h.observe(10.0, n=99)                   # one fenced window, 99 steps
    h.observe(1000.0, n=1)                  # one straggler
    assert h.count == 100
    assert h.quantile(0.5) == 10.0
    assert h.quantile(0.95) == 10.0
    assert h.max == 1000.0
    with pytest.raises(ValueError):
        h.observe(1.0, n=0)


def test_label_collisions_raise():
    reg = tm.MetricsRegistry()
    reg.counter("x")
    with pytest.raises(ValueError, match="one name, one schema"):
        reg.gauge("x")                      # kind collision
    reg.counter("y", labels={"a": "1"})
    with pytest.raises(ValueError, match="one name, one schema"):
        reg.counter("y")                    # label-KEY-set collision
    # distinct label VALUES are distinct series under the same schema
    other = reg.counter("y", labels={"a": "2"})
    assert other is not reg.counter("y", labels={"a": "1"})


def test_prometheus_exposition():
    reg = tm.MetricsRegistry()
    reg.counter("steps_total").inc(7)
    reg.gauge("loss", labels={"split": "eval"}).set(0.25)
    h = reg.histogram("step_time_ms")
    h.observe(2.0, n=9)
    h.observe(8.0)
    text = reg.prometheus_text()
    assert "# TYPE steps_total counter" in text
    assert "steps_total 7" in text
    assert 'loss{split="eval"} 0.25' in text
    assert "# TYPE step_time_ms summary" in text
    assert 'step_time_ms{quantile="0.5"} 2' in text
    assert "step_time_ms_count 10" in text


def test_append_jsonl_schema_versioned(tmp_path):
    path = str(tmp_path / "m.jsonl")
    out = tm.append_jsonl(path, {"epoch": 1})
    assert out["schema"] == 2 and out["epoch"] == 1
    rec = json.loads(open(path).read())
    assert rec["schema"] == 2 and "time" in rec
    # an explicit schema in the record wins over the default
    out2 = tm.append_jsonl(path, {"schema": 3, "epoch": 2})
    assert out2["schema"] == 3


# -- StepTimer ------------------------------------------------------------

def test_step_timer_compile_vs_steady_split_on_jitted_step():
    @jax.jit
    def step(x):
        return x @ x

    x = jnp.ones((64, 64))
    st = tm.StepTimer()
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(step(x))
        st.record_window(time.perf_counter() - t0, steps=1, examples=64)
    # first fenced window (trace+compile+first step) is split out
    assert st.compile_time_s is not None and st.compile_time_s > 0
    assert st.steps == 4
    p50_s = st.quantile_ms(0.5) / 1e3
    assert st.compile_time_s > p50_s        # compiling dwarfs a 64x64 matmul
    assert st.examples_per_sec > 0
    s = st.summary()
    assert s["steps"] == 4
    assert s["step_time_ms_p95"] >= s["step_time_ms_p50"] > 0
    assert s["step_time_ms_max"] >= s["step_time_ms_p95"]
    assert s["tokens_per_sec"] is None      # none were reported


def test_step_timer_windowed_weighting():
    st = tm.StepTimer()
    st.record_window(10.0, steps=1)                      # compile
    st.record_window(1.0, steps=10, examples=100)        # 100ms/step x10
    st.record_window(0.2, steps=1, examples=10)          # one 200ms step
    assert st.steps == 11
    assert st.quantile_ms(0.5) == 100.0
    assert st.summary()["step_time_ms_max"] == 200.0
    assert st.examples_per_sec == pytest.approx(110 / 1.2)


def test_compiled_cost_stats_best_effort():
    @jax.jit
    def f(x):
        return (x @ x).sum()

    sds = jax.ShapeDtypeStruct((32, 32), jnp.float32)
    stats = tm.compiled_cost_stats(f, sds)
    # the backend may or may not expose a cost model; the contract is
    # "dict with positive flops, or None" and never an exception
    assert stats is None or stats["flops"] > 0


# -- tracer ---------------------------------------------------------------

def test_chrome_trace_well_formed(tmp_path):
    tr = tm.Tracer()
    with tr.span("outer", epoch=1):
        with tr.span("inner"):
            time.sleep(0.002)
    tr.instant("marker")
    path = tr.write(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    events = {e["name"]: e for e in doc["traceEvents"]}
    assert {"process_name", "outer", "inner", "marker"} <= set(events)
    for name in ("outer", "inner"):
        ev = events[name]
        assert ev["ph"] == "X" and ev["dur"] >= 0
        assert {"ts", "pid", "tid"} <= set(ev)
    inner, outer = events["inner"], events["outer"]
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert events["outer"]["args"] == {"epoch": 1}
    assert events["marker"]["ph"] == "i"


def test_span_closes_on_exception():
    tr = tm.Tracer()
    with pytest.raises(RuntimeError):
        with tr.span("failing"):
            raise RuntimeError("boom")
    names = [e["name"] for e in tr.to_chrome_trace()["traceEvents"]]
    assert "failing" in names               # the failing interval is kept


# -- bubble model ---------------------------------------------------------

def test_bubble_fraction_schedule_model():
    assert tm.schedule_bubble_fraction(1, 1) == 0.0
    assert tm.schedule_bubble_fraction(1, 8, "1f1b") == 0.0
    assert tm.schedule_bubble_fraction(4, 8) == pytest.approx(3 / 11)
    # more microbatches -> smaller bubble, monotonically
    fr = [tm.schedule_bubble_fraction(4, m) for m in (1, 2, 4, 8, 16)]
    assert fr == sorted(fr, reverse=True)
    # non-interleaved 1F1B never exceeds GPipe (equality: same fill/drain)
    for s in (2, 3, 4, 8):
        for m in (1, 2, 4, 8):
            assert (tm.schedule_bubble_fraction(s, m, "1f1b")
                    <= tm.schedule_bubble_fraction(s, m, "gpipe"))
    with pytest.raises(ValueError, match="unknown schedule"):
        tm.schedule_bubble_fraction(2, 2, "interleaved")


def test_ideal_step_time_anchors_measured():
    # S=2, M=1: bubble 0.5 -> ideal is half the measured step
    assert tm.ideal_step_time(1.0, 2, 1) == pytest.approx(0.5)
    # single stage: already bubble-free
    assert tm.ideal_step_time(1.0, 1, 4) == pytest.approx(1.0)


# -- static ICI gauge -----------------------------------------------------

def test_expected_ici_bytes_ranks_pipeline_hops():
    from simple_distributed_machine_learning_tpu.analysis import abstractify
    from simple_distributed_machine_learning_tpu.train.optimizer import sgd
    from simple_distributed_machine_learning_tpu.train.step import (
        make_train_step,
    )

    stages, wire_dim, out_dim = make_mlp_stages(jax.random.key(0),
                                                [784, 32, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=2)
    step = make_train_step(pipe, sgd(0.1, 0.5))
    buf = pipe.init_params()
    opt_state = sgd(0.1, 0.5).init(buf)
    x = jnp.zeros((60, 784))
    y = jnp.zeros((60,), jnp.int32)
    info = tm.expected_ici_bytes(
        step, abstractify(buf), abstractify(opt_state), abstractify(x),
        abstractify(y), abstractify(jax.random.key(0)), None, mesh=mesh)
    assert info is not None
    assert info["ici_bytes_per_step"] > 0
    prims = {c["prim"] for c in info["collectives"]}
    assert "ppermute" in prims              # the stage-hop ring dominates
    # registry mirroring
    reg = tm.MetricsRegistry()
    from simple_distributed_machine_learning_tpu.telemetry import ici
    ici.record(reg, info)
    assert reg.snapshot()["ici_bytes_per_step"] == info["ici_bytes_per_step"]


def test_expected_ici_bytes_never_raises():
    def broken(x):
        raise TypeError("untraceable")

    assert tm.expected_ici_bytes(
        broken, jax.ShapeDtypeStruct((2,), jnp.float32)) is None


# -- hardened profiler.trace ----------------------------------------------

def test_profiler_trace_disabled_and_bad_logdir(tmp_path, capsys):
    from simple_distributed_machine_learning_tpu.utils.profiler import trace

    with trace(enabled=False) as d:
        assert d is None                    # nothing created, nothing started
    blocker = tmp_path / "a_file"
    blocker.write_text("not a dir")
    with trace(str(blocker / "sub")) as d:  # makedirs must fail
        assert d is None                    # degraded to disabled, no raise


def test_profiler_trace_no_leak_on_body_exception(tmp_path):
    from simple_distributed_machine_learning_tpu.utils.profiler import trace

    with pytest.raises(RuntimeError, match="boom"):
        with trace(str(tmp_path / "t1")):
            jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
            raise RuntimeError("boom")
    # the first trace was stopped despite the exception: a fresh one starts
    with trace(str(tmp_path / "t2")) as d:
        assert d == str(tmp_path / "t2")
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    found = [f for _, _, fs in os.walk(tmp_path / "t2") for f in fs]
    assert found, "second trace produced no files: first one leaked"


# -- Trainer smoke with a full session ------------------------------------

def _toy_trainer(tmp_path, tele, epochs=2, n_train=240):
    train, test = synthetic_mnist(n_train=n_train, n_test=60, seed=7)
    train = Dataset(train.x.reshape(len(train.x), -1), train.y)
    test = Dataset(test.x.reshape(len(test.x), -1), test.y)
    stages, wire_dim, out_dim = make_mlp_stages(jax.random.key(0),
                                                [784, 32, 10], 2)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=1), wire_dim,
                    out_dim, n_microbatches=2)
    cfg = TrainConfig(epochs=epochs, batch_size=60, print_throughput=False,
                      metrics_json=str(tmp_path / "metrics_v2.jsonl"))
    return Trainer(pipe, train, test, cfg, telemetry=tele)


def test_trainer_smoke_emits_full_epoch_records(tmp_path):
    tele = tm.Telemetry(str(tmp_path / "tele"))
    _toy_trainer(tmp_path, tele).fit()

    lines = open(tmp_path / "tele" / tm.METRICS_FILE).read().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert [r["epoch"] for r in recs] == [1, 2]
    for r in recs:
        assert r["schema"] == 2
        # throughput AND memory fields on every record (the smoke contract)
        assert r["examples_per_sec"] > 0
        assert r["live_array_bytes"] > 0
        assert r["step_time_ms_p50"] > 0
        assert r["step_time_ms_p95"] >= r["step_time_ms_p50"]
        assert r["bubble_fraction"] == pytest.approx(1 / 3, abs=1e-4)  # S=2, M=2
        assert r["ici_bytes_per_step"] > 0
        # the training record rides along: documented keys intact
        assert {"train_loss", "eval_loss", "accuracy"} <= set(r)
    # compile split: only the run's FIRST step is a compile window, so
    # epoch 1 has batches-1 steady steps and epoch 2 adds all 4 of its own
    assert recs[0]["steps"] == 3 and recs[1]["steps"] == 7
    assert recs[0]["compile_time_s"] > 0

    trace = json.load(open(tmp_path / "tele" / tm.TRACE_FILE))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"feed", "step", "eval", "epoch_end"} <= names
    prom = open(tmp_path / "tele" / tm.PROM_FILE).read()
    assert "# TYPE step_time_ms summary" in prom
    assert "epochs_total 2" in prom

    # the --metrics-json stream stays intact AND schema-versioned
    v2 = [json.loads(ln)
          for ln in open(tmp_path / "metrics_v2.jsonl").read().splitlines()]
    assert all(r["schema"] == 2 and "accuracy" in r for r in v2)


def test_telemetry_every_n_fences_sparsely(tmp_path):
    tele = tm.Telemetry(str(tmp_path / "tele"), every=3)
    tr = _toy_trainer(tmp_path, tele, epochs=1, n_train=360)  # 6 batches
    tr.fit()
    [rec] = [json.loads(ln) for ln in
             open(tmp_path / "tele" / tm.METRICS_FILE).read().splitlines()]
    # batch 0 force-fenced (compile); steps 2..6 fence at seen%3==0 -> the
    # (2,3) window and the (4,5,6) window: 5 steady steps in 2 windows
    assert rec["steps"] == 5
    assert rec["compile_time_s"] > 0
    assert rec["step_time_ms_p50"] > 0


def test_telemetry_every_validates():
    with pytest.raises(ValueError, match="every"):
        tm.Telemetry("/tmp/unused_tele", every=0)


def test_trainer_without_telemetry_unchanged(tmp_path, capsys):
    """telemetry=None is the reference path: no files, same console."""
    _toy_trainer(tmp_path, None, epochs=1).fit()
    out = capsys.readouterr().out
    assert "Test set: Average loss:" in out
    assert not (tmp_path / "tele").exists()
