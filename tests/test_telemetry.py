"""telemetry/: registry semantics, StepTimer compile-vs-steady split,
Chrome-trace well-formedness, bubble model, the static ICI gauge, the
hardened profiler.trace, and a Trainer smoke run with a full session."""

import json
import os
import re
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from simple_distributed_machine_learning_tpu import telemetry as tm
from simple_distributed_machine_learning_tpu.data.mnist import (
    Dataset,
    synthetic_mnist,
)
from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
from simple_distributed_machine_learning_tpu.train.trainer import (
    TrainConfig,
    Trainer,
)


# -- registry -------------------------------------------------------------

def test_counter_is_monotonic():
    reg = tm.MetricsRegistry()
    c = reg.counter("steps")
    c.inc()
    c.inc(4)
    assert c.value == 5
    with pytest.raises(ValueError, match="monotonic"):
        c.inc(-1)
    assert c.value == 5
    # same (name, labels) -> the same live instrument, not a fork
    assert reg.counter("steps") is c


def test_gauge_and_snapshot_roundtrip():
    reg = tm.MetricsRegistry()
    reg.gauge("loss").set(1.5)
    reg.counter("n", labels={"stage": "0"}).inc(3)
    snap = reg.snapshot()
    assert snap["loss"] == 1.5
    assert snap["n{stage=0}"] == 3
    json.loads(json.dumps(snap))            # JSON-serializable as claimed


def test_histogram_quantiles_nearest_rank():
    h = tm.Histogram("lat")
    for v in range(1, 101):
        h.observe(float(v))
    assert h.count == 100
    assert h.quantile(0.5) == 50.0
    assert h.quantile(0.95) == 95.0
    assert h.quantile(0.0) == 1.0 and h.quantile(1.0) == 100.0
    assert h.max == 100.0
    with pytest.raises(ValueError):
        h.quantile(1.5)


def test_histogram_weighted_observations():
    h = tm.Histogram("lat")
    h.observe(10.0, n=99)                   # one fenced window, 99 steps
    h.observe(1000.0, n=1)                  # one straggler
    assert h.count == 100
    assert h.quantile(0.5) == 10.0
    assert h.quantile(0.95) == 10.0
    assert h.max == 1000.0
    with pytest.raises(ValueError):
        h.observe(1.0, n=0)


def test_label_collisions_raise():
    reg = tm.MetricsRegistry()
    reg.counter("x")
    with pytest.raises(ValueError, match="one name, one schema"):
        reg.gauge("x")                      # kind collision
    reg.counter("y", labels={"a": "1"})
    with pytest.raises(ValueError, match="one name, one schema"):
        reg.counter("y")                    # label-KEY-set collision
    # distinct label VALUES are distinct series under the same schema
    other = reg.counter("y", labels={"a": "2"})
    assert other is not reg.counter("y", labels={"a": "1"})


def test_prometheus_exposition():
    reg = tm.MetricsRegistry()
    reg.counter("steps_total").inc(7)
    reg.gauge("loss", labels={"split": "eval"}).set(0.25)
    h = reg.histogram("step_time_ms")
    h.observe(2.0, n=9)
    h.observe(8.0)
    text = reg.prometheus_text()
    assert "# TYPE steps_total counter" in text
    assert "steps_total 7" in text
    assert 'loss{split="eval"} 0.25' in text
    assert "# TYPE step_time_ms summary" in text
    assert 'step_time_ms{quantile="0.5"} 2' in text
    assert "step_time_ms_count 10" in text


def test_prometheus_help_lines_from_catalog():
    """# HELP precedes # TYPE for every cataloged name — the docstring-
    sourced catalog (telemetry/catalog.py) is the text's one source."""
    reg = tm.MetricsRegistry()
    reg.counter("serve_requests_completed_total").inc()
    reg.gauge("some_uncataloged_metric").set(1)
    text = reg.prometheus_text()
    lines = text.splitlines()
    i = lines.index("# TYPE serve_requests_completed_total counter")
    assert lines[i - 1].startswith("# HELP serve_requests_completed_total ")
    # uncataloged names emit no HELP (never a fabricated one)
    assert "# HELP some_uncataloged_metric" not in text
    assert "# TYPE some_uncataloged_metric gauge" in text


_PROM_LINE = re.compile(
    r'^([A-Za-z_][A-Za-z0-9_]*)'
    r'(?:\{((?:[A-Za-z_][A-Za-z0-9_]*="(?:[^"\\\n]|\\["\\n])*",?)*)\})? '
    r'(-?[0-9.eE+-]+|NaN)$')


def test_prometheus_label_values_escaped_and_parseable():
    """THE satellite pin: a label value containing quotes, backslashes and
    newlines must still produce series every line of which matches the
    exposition grammar — previously `cls='a\"b'` emitted an unscrapeable
    line."""
    reg = tm.MetricsRegistry()
    reg.counter("serve_shed_total",
                labels={"reason": 'dead"line'}).inc(2)
    reg.gauge("g", labels={"cls": 'a\\b\nc"d'}).set(1)
    h = reg.histogram("h", labels={"cls": 'q"'})
    h.observe(1.0)
    text = reg.prometheus_text()
    assert '\\"' in text and "\\n" in text
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        assert _PROM_LINE.match(line), f"unparseable series line: {line!r}"
    # the escaped payload round-trips to the original value
    m = next(line for line in text.splitlines()
             if line.startswith("serve_shed_total"))
    inner = m[m.index("{") + 1:m.rindex("}")]
    val = inner.split("=", 1)[1].strip('"')
    assert (val.replace(r'\"', '"').replace(r'\n', '\n')
            .replace('\\\\', '\\') == 'dead"line')


def test_append_jsonl_schema_versioned(tmp_path):
    path = str(tmp_path / "m.jsonl")
    out = tm.append_jsonl(path, {"epoch": 1})
    assert out["schema"] == 2 and out["epoch"] == 1
    rec = json.loads(open(path).read())
    assert rec["schema"] == 2 and "time" in rec
    # an explicit schema in the record wins over the default
    out2 = tm.append_jsonl(path, {"schema": 3, "epoch": 2})
    assert out2["schema"] == 3


# -- StepTimer ------------------------------------------------------------

def test_step_timer_compile_vs_steady_split_on_jitted_step():
    @jax.jit
    def step(x):
        return x @ x

    x = jnp.ones((64, 64))
    st = tm.StepTimer()
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(step(x))
        st.record_window(time.perf_counter() - t0, steps=1, examples=64)
    # first fenced window (trace+compile+first step) is split out
    assert st.compile_time_s is not None and st.compile_time_s > 0
    assert st.steps == 4
    p50_s = st.quantile_ms(0.5) / 1e3
    assert st.compile_time_s > p50_s        # compiling dwarfs a 64x64 matmul
    assert st.examples_per_sec > 0
    s = st.summary()
    assert s["steps"] == 4
    assert s["step_time_ms_p95"] >= s["step_time_ms_p50"] > 0
    assert s["step_time_ms_max"] >= s["step_time_ms_p95"]
    assert s["tokens_per_sec"] is None      # none were reported


def test_step_timer_windowed_weighting():
    st = tm.StepTimer()
    st.record_window(10.0, steps=1)                      # compile
    st.record_window(1.0, steps=10, examples=100)        # 100ms/step x10
    st.record_window(0.2, steps=1, examples=10)          # one 200ms step
    assert st.steps == 11
    assert st.quantile_ms(0.5) == 100.0
    assert st.summary()["step_time_ms_max"] == 200.0
    assert st.examples_per_sec == pytest.approx(110 / 1.2)


def test_compiled_cost_stats_best_effort():
    @jax.jit
    def f(x):
        return (x @ x).sum()

    sds = jax.ShapeDtypeStruct((32, 32), jnp.float32)
    stats = tm.compiled_cost_stats(f, sds)
    # the backend may or may not expose a cost model; the contract is
    # "dict with positive flops, or None" and never an exception
    assert stats is None or stats["flops"] > 0


# -- tracer ---------------------------------------------------------------

def test_chrome_trace_well_formed(tmp_path):
    tr = tm.Tracer()
    with tr.span("outer", epoch=1):
        with tr.span("inner"):
            time.sleep(0.002)
    tr.instant("marker")
    path = tr.write(str(tmp_path / "trace.json"))
    doc = json.load(open(path))
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    events = {e["name"]: e for e in doc["traceEvents"]}
    assert {"process_name", "outer", "inner", "marker"} <= set(events)
    for name in ("outer", "inner"):
        ev = events[name]
        assert ev["ph"] == "X" and ev["dur"] >= 0
        assert {"ts", "pid", "tid"} <= set(ev)
    inner, outer = events["inner"], events["outer"]
    assert inner["ts"] >= outer["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert events["outer"]["args"] == {"epoch": 1}
    assert events["marker"]["ph"] == "i"


def test_span_closes_on_exception():
    tr = tm.Tracer()
    with pytest.raises(RuntimeError):
        with tr.span("failing"):
            raise RuntimeError("boom")
    names = [e["name"] for e in tr.to_chrome_trace()["traceEvents"]]
    assert "failing" in names               # the failing interval is kept


def test_async_events_keyed_by_id_with_explicit_ts():
    """Chrome b/e async events: interleaved spans under distinct ids stay
    distinct (no ts-containment nesting), explicit ts_us is honored
    verbatim (the serve recorder's virtual-clock stamps), and a pinned pid
    overrides the real one."""
    tr = tm.Tracer(pid=0)
    tr.async_begin("request", 1, ts_us=10.0, cat="req", cls="a")
    tr.async_begin("request", 2, ts_us=15.0, cat="req")    # interleaves
    tr.async_end("request", 1, ts_us=30.0, cat="req")
    tr.async_instant("tick", 2, ts_us=31.0, cat="req", tokens=3)
    tr.async_end("request", 2, ts_us=40.0, cat="req")
    evs = [e for e in tr.to_chrome_trace()["traceEvents"]
           if e["ph"] in ("b", "e", "n")]
    assert [(e["ph"], e["id"], e["ts"]) for e in evs] == [
        ("b", "1", 10.0), ("b", "2", 15.0), ("e", "1", 30.0),
        ("n", "2", 31.0), ("e", "2", 40.0)]
    assert all(e["cat"] == "req" and e["pid"] == 0 for e in evs)
    assert evs[0]["args"] == {"cls": "a"}
    assert evs[3]["args"] == {"tokens": 3}


def test_tracer_thread_safe_under_concurrent_emission():
    """The satellite pin: span/instant/async emission from many threads
    concurrently loses no events and corrupts no structure."""
    import threading

    tr = tm.Tracer()
    n_threads, per_thread = 8, 50
    barrier = threading.Barrier(n_threads)

    def work(tid):
        barrier.wait()
        for i in range(per_thread):
            with tr.span(f"span-{tid}", i=i):
                pass
            tr.instant(f"mark-{tid}")
            tr.async_begin("req", f"{tid}-{i}", ts_us=float(i))
            tr.async_end("req", f"{tid}-{i}", ts_us=float(i) + 1)

    threads = [threading.Thread(target=work, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    events = tr.to_chrome_trace()["traceEvents"]
    by_ph = {}
    for e in events:
        by_ph[e["ph"]] = by_ph.get(e["ph"], 0) + 1
    total = n_threads * per_thread
    assert by_ph["X"] == total and by_ph["i"] == total
    assert by_ph["b"] == total and by_ph["e"] == total
    # every async begin has its end, per id
    begins = {e["id"] for e in events if e["ph"] == "b"}
    ends = {e["id"] for e in events if e["ph"] == "e"}
    assert begins == ends and len(begins) == total
    json.dumps(events)                      # structurally intact


# -- bubble model ---------------------------------------------------------

def test_bubble_fraction_schedule_model():
    assert tm.schedule_bubble_fraction(1, 1) == 0.0
    assert tm.schedule_bubble_fraction(1, 8, "1f1b") == 0.0
    assert tm.schedule_bubble_fraction(4, 8) == pytest.approx(3 / 11)
    # more microbatches -> smaller bubble, monotonically
    fr = [tm.schedule_bubble_fraction(4, m) for m in (1, 2, 4, 8, 16)]
    assert fr == sorted(fr, reverse=True)
    # non-interleaved 1F1B never exceeds GPipe (equality: same fill/drain)
    for s in (2, 3, 4, 8):
        for m in (1, 2, 4, 8):
            assert (tm.schedule_bubble_fraction(s, m, "1f1b")
                    <= tm.schedule_bubble_fraction(s, m, "gpipe"))
    with pytest.raises(ValueError, match="unknown schedule"):
        tm.schedule_bubble_fraction(2, 2, "interleaved")


def test_ideal_step_time_anchors_measured():
    # S=2, M=1: bubble 0.5 -> ideal is half the measured step
    assert tm.ideal_step_time(1.0, 2, 1) == pytest.approx(0.5)
    # single stage: already bubble-free
    assert tm.ideal_step_time(1.0, 1, 4) == pytest.approx(1.0)


def test_measured_bubble_and_drift():
    from simple_distributed_machine_learning_tpu.telemetry.bubble import (
        bubble_drift,
        measured_bubble_fraction,
    )

    # a measured step exactly matching the slot model: drift reads zero
    s, m = 4, 8
    model = tm.schedule_bubble_fraction(s, m)
    ideal = 1.0
    measured = ideal / (1.0 - model)
    assert measured_bubble_fraction(measured, ideal) == pytest.approx(model)
    assert bubble_drift(s, m, "gpipe", measured, ideal) == pytest.approx(0.0)
    # real stages idling longer than the model -> positive drift
    assert bubble_drift(s, m, "gpipe", measured * 1.5, ideal) > 0
    # a faster-than-ideal measurement clamps at 0 measured bubble
    assert measured_bubble_fraction(0.5, 1.0) == 0.0
    with pytest.raises(ValueError, match="step times"):
        measured_bubble_fraction(0.0, 1.0)


def test_session_emits_bubble_drift_with_reference(tmp_path):
    """set_bubble_reference turns the epoch record's modeled bubble into a
    checked one: measured + drift gauges appear only when a bubble-free
    reference was supplied (never fabricated from the model itself)."""
    class _Pipe:
        n_stages, n_microbatches, schedule = 2, 2, "gpipe"

    t = tm.Telemetry(str(tmp_path))
    t.timer.record_window(0.4, steps=4)          # compile window
    t.timer.record_window(0.4, steps=4)          # steady: 100 ms/step
    rec = t.epoch_record(0, pipe=_Pipe())
    assert "bubble_drift" not in rec             # no reference, no drift
    # bubble-free reference: ideal 66.67 ms -> measured == model -> drift 0
    model = tm.schedule_bubble_fraction(2, 2)
    t.set_bubble_reference(0.1 * (1.0 - model))
    rec = t.epoch_record(1, pipe=_Pipe())
    assert rec["bubble_fraction_measured"] == pytest.approx(model,
                                                            abs=2e-4)
    assert rec["bubble_drift"] == pytest.approx(0.0, abs=2e-4)
    with pytest.raises(ValueError, match="ideal_step_s"):
        t.set_bubble_reference(0.0)


# -- static ICI gauge -----------------------------------------------------

def test_expected_ici_bytes_ranks_pipeline_hops():
    from simple_distributed_machine_learning_tpu.analysis import abstractify
    from simple_distributed_machine_learning_tpu.train.optimizer import sgd
    from simple_distributed_machine_learning_tpu.train.step import (
        make_train_step,
    )

    stages, wire_dim, out_dim = make_mlp_stages(jax.random.key(0),
                                                [784, 32, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=2)
    step = make_train_step(pipe, sgd(0.1, 0.5))
    buf = pipe.init_params()
    opt_state = sgd(0.1, 0.5).init(buf)
    x = jnp.zeros((60, 784))
    y = jnp.zeros((60,), jnp.int32)
    info = tm.expected_ici_bytes(
        step, abstractify(buf), abstractify(opt_state), abstractify(x),
        abstractify(y), abstractify(jax.random.key(0)), None, mesh=mesh)
    assert info is not None
    assert info["ici_bytes_per_step"] > 0
    prims = {c["prim"] for c in info["collectives"]}
    assert "ppermute" in prims              # the stage-hop ring dominates
    # registry mirroring
    reg = tm.MetricsRegistry()
    from simple_distributed_machine_learning_tpu.telemetry import ici
    ici.record(reg, info)
    assert reg.snapshot()["ici_bytes_per_step"] == info["ici_bytes_per_step"]


def test_expected_ici_bytes_never_raises():
    def broken(x):
        raise TypeError("untraceable")

    assert tm.expected_ici_bytes(
        broken, jax.ShapeDtypeStruct((2,), jnp.float32)) is None


# -- hardened profiler.trace ----------------------------------------------

def test_profiler_trace_disabled_and_bad_logdir(tmp_path, capsys):
    from simple_distributed_machine_learning_tpu.utils.profiler import trace

    with trace(enabled=False) as d:
        assert d is None                    # nothing created, nothing started
    blocker = tmp_path / "a_file"
    blocker.write_text("not a dir")
    with trace(str(blocker / "sub")) as d:  # makedirs must fail
        assert d is None                    # degraded to disabled, no raise


def test_profiler_trace_no_leak_on_body_exception(tmp_path):
    from simple_distributed_machine_learning_tpu.utils.profiler import trace

    with pytest.raises(RuntimeError, match="boom"):
        with trace(str(tmp_path / "t1")):
            jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
            raise RuntimeError("boom")
    # the first trace was stopped despite the exception: a fresh one starts
    with trace(str(tmp_path / "t2")) as d:
        assert d == str(tmp_path / "t2")
        jax.block_until_ready(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    found = [f for _, _, fs in os.walk(tmp_path / "t2") for f in fs]
    assert found, "second trace produced no files: first one leaked"


# -- Trainer smoke with a full session ------------------------------------

def _toy_trainer(tmp_path, tele, epochs=2, n_train=240):
    train, test = synthetic_mnist(n_train=n_train, n_test=60, seed=7)
    train = Dataset(train.x.reshape(len(train.x), -1), train.y)
    test = Dataset(test.x.reshape(len(test.x), -1), test.y)
    stages, wire_dim, out_dim = make_mlp_stages(jax.random.key(0),
                                                [784, 32, 10], 2)
    pipe = Pipeline(stages, make_mesh(n_stages=2, n_data=1), wire_dim,
                    out_dim, n_microbatches=2)
    cfg = TrainConfig(epochs=epochs, batch_size=60, print_throughput=False,
                      metrics_json=str(tmp_path / "metrics_v2.jsonl"))
    return Trainer(pipe, train, test, cfg, telemetry=tele)


def test_trainer_smoke_emits_full_epoch_records(tmp_path):
    tele = tm.Telemetry(str(tmp_path / "tele"))
    _toy_trainer(tmp_path, tele).fit()

    lines = open(tmp_path / "tele" / tm.METRICS_FILE).read().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert [r["epoch"] for r in recs] == [1, 2]
    for r in recs:
        assert r["schema"] == 2
        # throughput AND memory fields on every record (the smoke contract)
        assert r["examples_per_sec"] > 0
        assert r["live_array_bytes"] > 0
        assert r["step_time_ms_p50"] > 0
        assert r["step_time_ms_p95"] >= r["step_time_ms_p50"]
        assert r["bubble_fraction"] == pytest.approx(1 / 3, abs=1e-4)  # S=2, M=2
        assert r["ici_bytes_per_step"] > 0
        # the training record rides along: documented keys intact
        assert {"train_loss", "eval_loss", "accuracy"} <= set(r)
    # compile split: only the run's FIRST step is a compile window, so
    # epoch 1 has batches-1 steady steps and epoch 2 adds all 4 of its own
    assert recs[0]["steps"] == 3 and recs[1]["steps"] == 7
    assert recs[0]["compile_time_s"] > 0

    trace = json.load(open(tmp_path / "tele" / tm.TRACE_FILE))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"feed", "step", "eval", "epoch_end"} <= names
    prom = open(tmp_path / "tele" / tm.PROM_FILE).read()
    assert "# TYPE step_time_ms summary" in prom
    assert "epochs_total 2" in prom

    # the --metrics-json stream stays intact AND schema-versioned
    v2 = [json.loads(ln)
          for ln in open(tmp_path / "metrics_v2.jsonl").read().splitlines()]
    assert all(r["schema"] == 2 and "accuracy" in r for r in v2)


def test_telemetry_every_n_fences_sparsely(tmp_path):
    tele = tm.Telemetry(str(tmp_path / "tele"), every=3)
    tr = _toy_trainer(tmp_path, tele, epochs=1, n_train=360)  # 6 batches
    tr.fit()
    [rec] = [json.loads(ln) for ln in
             open(tmp_path / "tele" / tm.METRICS_FILE).read().splitlines()]
    # batch 0 force-fenced (compile); steps 2..6 fence at seen%3==0 -> the
    # (2,3) window and the (4,5,6) window: 5 steady steps in 2 windows
    assert rec["steps"] == 5
    assert rec["compile_time_s"] > 0
    assert rec["step_time_ms_p50"] > 0


def test_telemetry_every_validates():
    with pytest.raises(ValueError, match="every"):
        tm.Telemetry("/tmp/unused_tele", every=0)


def test_trainer_without_telemetry_unchanged(tmp_path, capsys):
    """telemetry=None is the reference path: no files, same console."""
    _toy_trainer(tmp_path, None, epochs=1).fit()
    out = capsys.readouterr().out
    assert "Test set: Average loss:" in out
    assert not (tmp_path / "tele").exists()
