"""Per-request TTFT attribution (ISSUE 19): the additive fold.

The acceptance pins: every component decomposition sums EXACTLY to the
request's journaled TTFT (reconciliation drift beyond float rounding is
an :class:`AttributionError`, i.e. a test failure), across the
queue-heavy, host-prefetch-gate, chunked-prefill, preemption,
crash-restart and fleet-handoff paths — including one recovered rid whose
timeline spans two engine incarnations — and the aggregated scenario
blocks are deterministic enough to pin byte-identically.
"""

import json

import jax
import pytest

from simple_distributed_machine_learning_tpu.models.gpt import (
    GPTConfig,
    make_gpt_stages,
)
from simple_distributed_machine_learning_tpu.resilience import faults
from simple_distributed_machine_learning_tpu.resilience.scenarios import (
    run_scenario,
)
from simple_distributed_machine_learning_tpu.serve.tracing import ServeTrace
from simple_distributed_machine_learning_tpu.telemetry.attribution import (
    DRIFT_TOL_MS,
    AttributionError,
    attribute,
    fold_request,
)
from simple_distributed_machine_learning_tpu.telemetry.registry import (
    MetricsRegistry,
)

CFG = GPTConfig(vocab=32, seq_len=48, d_model=32, n_heads=2, n_layers=2)
_STAGES = None


def _model():
    global _STAGES
    if _STAGES is None:
        _STAGES = make_gpt_stages(jax.random.key(0), CFG, 2)[0]
    return _STAGES


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    faults.uninstall()
    yield
    faults.uninstall()


def _row(ev, t, rid=0, inc=0, **kw):
    return {"ev": ev, "t": t, "rid": rid, "inc": inc, **kw}


# ---------------------------------------------------------------------------
# the fold: synthetic timelines, every span→component edge


def test_fold_simple_queue_prefill_decode():
    att = fold_request([
        _row("submit", 0.0, cls="x", prompt_len=4),
        _row("admit", 0.010),
        _row("first_token", 0.030, ttft_ms=30.0),
        _row("tick", 0.040),
        _row("done", 0.050, tokens=3, reason="length"),
    ])
    assert att["components_ms"] == {"queue": 10.0, "prefill": 20.0}
    assert att["ttft_ms"] == 30.0 and att["drift_ms"] == 0.0
    assert att["cls"] == "x" and att["prompt_len"] == 4
    assert att["incarnations"] == [0] and not att["recovered"]
    # the decode side aggregates separately (the TPOT block)
    assert att["decode_ms"] == 20.0
    assert att["decode_components_ms"] == {"decode": 20.0}
    assert att["tokens"] == 3 and att["finish"] == "length"


def test_fold_prefetch_gate_chunks_and_preemption():
    """Host-prefetch gate wait, chunked prefill (inter-chunk spans stay
    prefill), a preemption with readmission: the full pre-TTFT map."""
    att = fold_request([
        _row("submit", 0.0, cls="x", prompt_len=8),
        _row("gate", 0.005),                 # blocked on host->HBM upload
        _row("admit", 0.009),
        _row("prefill_chunk", 0.012),
        _row("preempt", 0.020),              # evicted mid-prefill
        _row("readmit", 0.024),              # re-boards: the wait after
        _row("admit", 0.026),                # readmission is queue again
        _row("first_token", 0.040, ttft_ms=40.0),
    ])
    assert att["components_ms"] == {
        "queue": 7.0, "prefetch": 4.0, "prefill": 25.0, "preempt": 4.0}
    assert sum(att["components_ms"].values()) == att["ttft_ms"] == 40.0


def test_fold_crash_spans_incarnations():
    """A recovered rid: the crash->readmit->board gap stays ``crash``
    (readmit does NOT flip it to queue — the outage caused the wait), and
    the rid-less restart row never breaks the cursor walk."""
    att = fold_request([
        _row("submit", 0.0, cls="x", prompt_len=4),
        _row("admit", 0.002),
        _row("crash", 0.010),
        {"ev": "restart", "t": 0.011, "inc": 1},
        _row("readmit", 0.015, inc=1),
        _row("admit", 0.016, inc=1),
        _row("first_token", 0.020, inc=1, ttft_ms=20.0),
    ])
    assert att["components_ms"] == {
        "queue": 2.0, "prefill": 12.0, "crash": 6.0}
    assert att["incarnations"] == [0, 1] and att["recovered"]


def test_fold_handoff_migration():
    att = fold_request([
        _row("submit", 0.0, cls="x", prompt_len=4),
        _row("admit", 0.004),
        _row("migrate", 0.010),
        _row("readmit", 0.012),              # still the handoff gap
        _row("admit", 0.013),
        _row("first_token", 0.020, ttft_ms=20.0),
    ])
    assert att["components_ms"] == {
        "queue": 4.0, "prefill": 13.0, "handoff": 3.0}


def test_fold_drift_raises_and_shed_returns_none():
    rows = [
        _row("submit", 0.0, cls="x", prompt_len=4),
        _row("admit", 0.010),
        _row("first_token", 0.030, ttft_ms=99.0),   # timeline disagrees
    ]
    with pytest.raises(AttributionError):
        fold_request(rows)
    # nothing to decompose: never reached a first token
    assert fold_request([
        _row("submit", 0.0, cls="x", prompt_len=4),
        _row("shed", 0.001, reason="deadline"),
    ]) is None


def test_attribute_aggregates_and_registers_histograms():
    reg = MetricsRegistry()
    rows = [
        _row("submit", 0.0, rid=0, cls="a", prompt_len=4),
        _row("admit", 0.010, rid=0),
        _row("first_token", 0.030, rid=0, ttft_ms=30.0),
        _row("submit", 0.001, rid=1, cls="a", prompt_len=4),
        _row("admit", 0.002, rid=1),
        _row("first_token", 0.041, rid=1, ttft_ms=40.0),
        _row("submit", 0.002, rid=2, cls="b", prompt_len=4),
        _row("shed", 0.003, rid=2, reason="class"),
    ]
    out = attribute(rows, registry=reg)
    assert out["requests"] == 2 and out["recovered"] == 0
    assert out["by_class"]["a"]["n"] == 2
    assert out["by_class"]["a"]["ttft_ms_mean"] == 35.0
    assert out["by_class"]["a"]["components_ms_mean"] == {
        "queue": 5.5, "prefill": 29.5}
    # slowest first, rid ascending on ties
    assert [a["rid"] for a in out["top_slow"]] == [1, 0]
    assert out["max_abs_drift_ms"] <= DRIFT_TOL_MS
    prom = reg.prometheus_text()
    assert 'serve_ttft_component_ms_count{component="queue"} 2' in prom
    assert 'serve_ttft_component_ms_count{component="prefill"} 2' in prom


# ---------------------------------------------------------------------------
# the scenario pins: reconciliation on every real path, exact numbers


def test_attribution_reconciles_across_every_serving_path():
    """One assertion per acceptance path: queue-heavy shed storm,
    crash-restart, host-offload prefetch, fleet handoff, disaggregated
    chunked prefill — every fold reconciles (drift within float
    rounding), with the per-scenario request counts pinned."""
    expected_requests = {
        "overload-shed": 11,          # queue-heavy: only completions fold
        "crash-serve": 16,
        "offload-churn": 24,          # host-prefetch gate path
        "handoff-replica-loss": 16,   # fleet handoff path
        "disagg-prefill-heavy": 16,   # chunked-prefill pools
    }
    for name, n in expected_requests.items():
        rep = run_scenario(name, _model(), CFG, trace=True)
        att = rep["attribution"]
        assert att["requests"] == n, name
        assert att["max_abs_drift_ms"] <= DRIFT_TOL_MS, name
        for a in att["top_slow"]:
            assert sum(a["components_ms"].values()) == pytest.approx(
                a["ttft_ms"], abs=DRIFT_TOL_MS), (name, a["rid"])


def test_crash_serve_autopsy_pinned_with_recovered_rid():
    """The crash-restart pin, exact virtual-clock numbers: the slowest
    request's autopsy and the one rid whose timeline spans both engine
    incarnations (recovered through the journal)."""
    tr = ServeTrace()
    rep = run_scenario("crash-serve", _model(), CFG, trace=tr)
    att = rep["attribution"]
    assert att["requests"] == 16 and att["recovered"] == 1
    assert att["max_abs_drift_ms"] == 0.0
    top = att["top_slow"][0]
    assert top["rid"] == 3 and top["ttft_ms"] == 23.16
    assert top["components_ms"] == {"queue": 1.16, "prefill": 22.0}
    # the recovered rid, folded straight from its two-incarnation rows
    rows0 = [r for r in tr.rows if r.get("rid") == 0]
    a0 = fold_request(rows0)
    assert a0["incarnations"] == [0, 1] and a0["recovered"]
    assert sum(a0["components_ms"].values()) == pytest.approx(
        a0["ttft_ms"], abs=DRIFT_TOL_MS)
    # the pre-existing crash pins survive attribution riding along
    assert rep["restarts"] == 1 and rep["slo_ok"]


def test_overload_shed_autopsy_pinned():
    rep = run_scenario("overload-shed", _model(), CFG, trace=True)
    att = rep["attribution"]
    assert att["requests"] == 11
    top = att["top_slow"][0]
    assert top["rid"] == 2 and top["cls"] == "batch"
    assert top["ttft_ms"] == 351.149
    assert top["components_ms"] == {"queue": 333.15, "prefill": 18.0}


def test_attribution_block_deterministic():
    r1 = run_scenario("crash-serve", _model(), CFG, trace=True)
    r2 = run_scenario("crash-serve", _model(), CFG, trace=True)
    assert (json.dumps(r1["attribution"], sort_keys=True)
            == json.dumps(r2["attribution"], sort_keys=True))
