"""One-off block-size tuning sweep for the Pallas flash kernel on chip.

Times fwd+bwd at several (block_q, block_k) against XLA dense, bf16,
dh in {64, 128}, T in {2048, 4096, 8192}. Prints one JSON line per point.
"""

import functools
import json
import time

import jax
import jax.numpy as jnp


def _time(fn, *args, reps=10):
    out = fn(*args)
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(*args)
        jax.block_until_ready(out)
        float(jax.tree.leaves(out)[0].ravel()[0])
        best = min(best, (time.perf_counter() - t0) / reps)
    return best * 1e3


def main():
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from simple_distributed_machine_learning_tpu.ops.attention import (
        causal_attention_core,
    )
    from simple_distributed_machine_learning_tpu.ops.flash_attention import (
        flash_attention,
    )

    B, H = 4, 8
    for dh in (64, 128):
        for t in (2048, 4096, 8192):
            key = jax.random.key(0)
            kq, kk, kv = jax.random.split(key, 3)
            shape = (B, H, t, dh)
            q = jax.random.normal(kq, shape).astype(jnp.bfloat16)
            k = jax.random.normal(kk, shape).astype(jnp.bfloat16)
            v = jax.random.normal(kv, shape).astype(jnp.bfloat16)

            def fwd_bwd(attn, q, k, v):
                def loss(q, k, v):
                    return jnp.sum(attn(q, k, v).astype(jnp.float32) ** 2)
                return jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)

            dense_oom = False
            try:
                dense_ms = _time(jax.jit(functools.partial(
                    fwd_bwd, causal_attention_core)), q, k, v)
            except Exception as e:
                # only a memory failure is the flash kernel's win; anything
                # else (compile/lowering error) must not masquerade as one
                dense_ms = None
                dense_oom = ("RESOURCE_EXHAUSTED" in str(e)
                             or "memory" in str(e).lower())
                print(json.dumps({"t": t, "dh": dh,
                                  "dense": f"FAIL {str(e)[:120]}",
                                  "dense_oom": dense_oom}))
            # trimmed grid: every point costs a fwd+bwd XLA compile on chip
            # (~30-45 s through the tunnel), and overrunning the step timeout
            # risks a mid-dispatch SIGTERM wedge. (128,128) is the default
            # baseline; larger bq cuts K/V passes (the r4 refetch diagnosis),
            # larger bk cuts grid steps.
            for bq, bk in ((128, 128), (256, 256), (256, 512),
                           (512, 256), (512, 512), (512, 1024)):
                if bq > t or bk > t:
                    continue
                attn = functools.partial(flash_attention,
                                         block_q=bq, block_k=bk)
                try:
                    ms = _time(jax.jit(functools.partial(fwd_bwd, attn)),
                               q, k, v)
                    print(json.dumps({
                        "t": t, "dh": dh, "bq": bq, "bk": bk,
                        "flash_ms": round(ms, 3),
                        "dense_ms": (round(dense_ms, 3)
                                     if dense_ms is not None else None),
                        "dense_oom": dense_oom,
                        "speedup": (round(dense_ms / ms, 2)
                                    if dense_ms is not None else None)}))
                except Exception as e:
                    print(json.dumps({"t": t, "dh": dh, "bq": bq,
                                      "bk": bk,
                                      "err": str(e)[:120]}))
                sys.stdout.flush()


if __name__ == "__main__":
    main()
