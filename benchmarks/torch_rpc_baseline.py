"""CPU baseline: the reference's architecture, measured.

A fresh implementation (not a copy) of the reference's design — two processes,
pipeline-split model, torch.distributed.rpc transport, distributed autograd,
DistributedOptimizer (see SURVEY.md §0/§3 for the architecture being
reproduced) — on BASELINE.json config 1: a 2-layer MLP split rank0=fc1 /
rank1=fc2, random tensors, batch 60, SGD(lr=0.1, momentum=0.5).

Run directly: prints ``RESULT{json}`` with steady-state samples/sec. This is
the number the TPU build's ``bench.py`` divides by for ``vs_baseline``.
"""

from __future__ import annotations

import json
import os
import time

import torch
import torch.distributed.autograd as dist_autograd
import torch.distributed.rpc as rpc
import torch.multiprocessing as mp
import torch.nn as nn
from torch.distributed.optim import DistributedOptimizer
from torch.distributed.rpc import RRef

DIMS = (784, 512, 10)
BATCH = 60
WARMUP = 20
STEPS = 100


class BackHalf(nn.Module):
    """fc2 + log_softmax, hosted on the worker process."""

    def __init__(self):
        super().__init__()
        self.fc2 = nn.Linear(DIMS[1], DIMS[2])

    def forward(self, x_rref: RRef) -> torch.Tensor:
        x = x_rref.to_here()
        return torch.log_softmax(self.fc2(x), dim=1)

    def param_rrefs(self):
        return [RRef(p) for p in self.parameters()]


class FrontHalf(nn.Module):
    """fc1 on the master; holds the remote handle to the back half."""

    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(DIMS[0], DIMS[1])
        self.back = rpc.remote("worker", BackHalf)

    def forward(self, x: torch.Tensor) -> torch.Tensor:
        h = torch.relu(self.fc1(x))
        return self.back.rpc_sync().forward(RRef(h))

    def all_param_rrefs(self):
        local = [RRef(p) for p in self.parameters()]
        return local + self.back.rpc_sync().param_rrefs()


def run_master() -> None:
    torch.manual_seed(0)
    model = FrontHalf()
    opt = DistributedOptimizer(
        torch.optim.SGD, model.all_param_rrefs(), lr=0.1, momentum=0.5)
    x = torch.randn(BATCH, DIMS[0])
    y = torch.randint(0, DIMS[2], (BATCH,))

    def one_step() -> None:
        with dist_autograd.context() as ctx:
            out = model(x)
            loss = torch.nn.functional.nll_loss(out, y)
            dist_autograd.backward(ctx, [loss])
            opt.step(ctx)

    for _ in range(WARMUP):
        one_step()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        one_step()
    dt = time.perf_counter() - t0
    print("RESULT" + json.dumps({
        "samples_per_sec": STEPS * BATCH / dt,
        "steps_per_sec": STEPS / dt,
        "impl": "torch_rpc_2proc_cpu",
    }), flush=True)


def _proc(rank: int) -> None:
    os.environ.setdefault("MASTER_ADDR", "127.0.0.1")
    os.environ.setdefault("MASTER_PORT", "29611")
    opts = rpc.TensorPipeRpcBackendOptions(num_worker_threads=16,
                                           rpc_timeout=120)
    name = "master" if rank == 0 else "worker"
    rpc.init_rpc(name, rank=rank, world_size=2, rpc_backend_options=opts)
    if rank == 0:
        run_master()
    rpc.shutdown()


def main() -> None:
    mp.start_processes(_proc, nprocs=2, start_method="spawn")


if __name__ == "__main__":
    main()
