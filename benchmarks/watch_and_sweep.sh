#!/usr/bin/env bash
# Patient follow-up sweep: probe the wedged tunnel every 10 minutes and, the
# moment it answers, run the round-5 remaining measurements with the strict
# single-client discipline (60 s settle between clients, generous timeouts,
# never kill a client mid-dispatch). See BASELINE.md incident notes.
#
# Steps (value order):
#   1. flash_tune block sweep        -> benchmarks/flash_tune.log
#   2. flash_timing (jaxref column)  -> benchmarks/flash_timing.json
#   3. bench --decode (fixed harness)-> benchmarks/decode_timing.json
#   4. gpt_bf16 with sgd lr=0.01     -> stdout row (experiment, no artifact)
set -u -o pipefail
cd "$(dirname "$0")/.."

probe() {
  timeout 90 python -c \
    "import jax, jax.numpy as jnp; print(float((jnp.ones((128,128))@jnp.ones((128,128))).sum()))" \
    >/dev/null 2>&1
}

deadline=$(( $(date +%s) + 8*3600 ))
n=0
while true; do
  n=$((n+1))
  echo "[watch] probe #$n $(date -u +%H:%M:%S)"
  if probe; then
    echo "[watch] tunnel ALIVE at $(date -u +%H:%M:%S) - starting sweep"
    break
  fi
  if [ "$(date +%s)" -ge "$deadline" ]; then
    echo "[watch] 8h deadline reached, tunnel never answered - giving up"
    exit 17
  fi
  sleep 600
done

# settle after the previous client, then re-probe before launching the next
# one: if a step's client timed out (SIGTERM mid-dispatch can re-wedge the
# tunnel for hours - BASELINE.md incident notes), burning the remaining
# steps' timeouts against a wedged tunnel only deepens the wedge. Probes at
# acquisition are safe to kill; clients mid-dispatch are not.
settle_probe() {
  sleep 60
  for i in 1 2 3; do
    if probe; then return 0; fi
    echo "[watch] inter-step probe $i/3 failed $(date -u +%H:%M:%S)"
    sleep 120
  done
  echo "[watch] tunnel wedged between steps - aborting remaining steps"
  exit 17
}

sleep 60
echo "[watch] 1/4 flash_tune block sweep"
timeout 3000 python benchmarks/flash_tune.py > benchmarks/flash_tune.log 2>&1 \
  || echo "[watch] flash_tune rc=$?"
settle_probe

echo "[watch] 2/4 flash_timing (incl. jaxref column)"
timeout 2400 python benchmarks/flash_timing.py || echo "[watch] flash_timing rc=$?"
settle_probe

echo "[watch] 3/4 bench --decode (fixed harness)"
timeout 1800 python bench.py --decode || echo "[watch] decode rc=$?"
settle_probe

echo "[watch] 4/4 gpt_bf16 sgd lr=0.01 stability/throughput probe"
timeout 1800 python bench.py --config gpt_bf16 --opt sgd --lr 0.01 \
  || echo "[watch] bf16-sgd rc=$?"

echo "[watch] done $(date -u +%H:%M:%S)"
