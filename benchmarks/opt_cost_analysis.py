"""Why did the gpt_bf16 bench row halve under AdamW? Compiled cost analysis.

Round-5 sweep: gpt_bf16 fell 3 838 -> 1 853 samples/sec (same flops/sample)
when the row's optimizer switched sgd(0.1, m=0.5) -> adamw(1e-3) for a
finite loss. AdamW's arithmetic is a handful of fused elementwise passes
(~0.5 ms of HBM traffic on this 12.6M-param model), nowhere near the
observed +4.4 ms/step — so compare the COMPILED programs, not the math:
XLA's cost analysis (flops / bytes accessed) and memory analysis for the
same scanned train step under each optimizer.

Runs entirely on CPU (compile-only, nothing executed): the suspicion is a
structural effect (scan-carry copies of the m/v state, remat interaction),
which shows up in bytes-accessed ratios on any backend.

Prints one JSON line per optimizer and a verdict line.
"""

import json
import os
import sys

# FORCE cpu: this environment exports JAX_PLATFORMS=axon, AND its
# sitecustomize imports jax at interpreter startup — so neither setdefault
# nor a plain env assignment here keeps this compile-only script off the
# single-client tunnel (two setdefault-era runs raced the flash_tune sweep
# client at device acquisition and killed it - see BASELINE.md). The env
# var covers fresh interpreters; the config update below re-latches the
# already-imported jax (same shim as bench.py::_apply_env_platform).
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.default_backend()


def main() -> None:
    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )
    from simple_distributed_machine_learning_tpu.train.optimizer import (
        adamw,
        sgd,
    )
    from simple_distributed_machine_learning_tpu.train.step import (
        make_scanned_train_step,
    )

    # the bench's gpt_bf16 spec (bench.py::_configs), smaller pool to keep
    # CPU compile time sane; per-step structure is what matters
    cfg = GPTConfig(vocab=1024, seq_len=128, d_model=256, n_heads=4,
                    n_layers=4)
    batch, pool, steps = 4, 2, 8
    stages, wire_dim, out_dim = make_gpt_stages(jax.random.key(0), cfg,
                                                n_stages=1)
    mesh = make_mesh(n_stages=1, n_data=1)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=1,
                    compute_dtype=jnp.bfloat16)
    buf = pipe.init_params()
    xs = jnp.zeros((pool, batch, cfg.seq_len), jnp.float32)
    ts = jnp.zeros((pool, batch, cfg.seq_len), jnp.int32)
    key = jax.random.key(0)

    from simple_distributed_machine_learning_tpu.train.optimizer import (
        Optimizer,
    )

    def adamw_folded(lr, b1=0.9, b2=0.999, eps=1e-8, wd=0.01) -> Optimizer:
        """torch-identical AdamW with bias correction folded into scalars:
        update = lr*sqrt(bc2)/bc1 * m / (sqrt(v) + eps*sqrt(bc2)), which is
        algebraically torch's lr/bc1 * m / (sqrt(v)/sqrt(bc2) + eps) — but
        avoids materializing m/bc1 and v/bc2 as full tensors."""

        def init(params):
            zeros = lambda: jax.tree.map(jnp.zeros_like, params)
            return (jnp.zeros((), jnp.int32), zeros(), zeros())

        def update(grads, state, params):
            step, m, v = state
            step = step + 1
            t = step.astype(jnp.float32)
            m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, m, grads)
            v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, v,
                             grads)
            rbc2 = jnp.sqrt(1 - b2 ** t)
            alpha = lr * rbc2 / (1 - b1 ** t)

            def upd(p, m_, v_):
                return p * (1 - lr * wd) - alpha * m_ / (
                    jnp.sqrt(v_) + eps * rbc2)

            return jax.tree.map(upd, params, m, v), (step, m, v)

        return Optimizer(init, update)

    def adamw_bf16state(lr) -> Optimizer:
        """AdamW with m/v stored in bf16 (halved state traffic; the update
        math still runs in f32 via upcast)."""
        inner = adamw_folded(lr)

        def init(params):
            step, m, v = inner.init(params)
            tobf = lambda t_: jax.tree.map(
                lambda x: x.astype(jnp.bfloat16), t_)
            return (step, tobf(m), tobf(v))

        def update(grads, state, params):
            step, m, v = state
            tof32 = lambda t_: jax.tree.map(
                lambda x: x.astype(jnp.float32), t_)
            new_params, (step, m, v) = inner.update(
                grads, (step, tof32(m), tof32(v)), params)
            tobf = lambda t_: jax.tree.map(
                lambda x: x.astype(jnp.bfloat16), t_)
            return new_params, (step, tobf(m), tobf(v))

        return Optimizer(init, update)

    def two_buffer_sgd(lr) -> Optimizer:
        """Isolation probe: TWO momentum-like state buffers, no counter, no
        scalar chain — pure extra-state cost."""

        def init(params):
            zeros = lambda: jax.tree.map(jnp.zeros_like, params)
            return (zeros(), zeros())

        def update(grads, state, params):
            m, v = state
            m = jax.tree.map(lambda m_, g: 0.9 * m_ + g, m, grads)
            v = jax.tree.map(lambda v_, g: 0.5 * v_ + g, v, grads)
            new_params = jax.tree.map(
                lambda p, m_, v_: p - lr * (m_ + v_), params, m, v)
            return new_params, (m, v)

        return Optimizer(init, update)

    def adamw_nobias(lr, eps=1e-8) -> Optimizer:
        """Isolation probe: m/v + sqrt update WITHOUT the step counter /
        bias-correction scalar chain."""

        def init(params):
            zeros = lambda: jax.tree.map(jnp.zeros_like, params)
            return (zeros(), zeros())

        def update(grads, state, params):
            m, v = state
            m = jax.tree.map(lambda m_, g: 0.9 * m_ + 0.1 * g, m, grads)
            v = jax.tree.map(lambda v_, g: 0.999 * v_ + 0.001 * g * g, v,
                             grads)
            new_params = jax.tree.map(
                lambda p, m_, v_: p - lr * m_ / (jnp.sqrt(v_) + eps),
                params, m, v)
            return new_params, (m, v)

        return Optimizer(init, update)

    def adamw_running(lr, b1=0.9, b2=0.999, eps=1e-8, wd=0.01) -> Optimizer:
        """torch-identical AdamW with the bias-correction powers carried as
        RUNNING PRODUCTS (b1pow *= b1 per step) instead of ``b1 ** t`` on a
        traced exponent — the pow-of-traced-scalar is the suspected
        fusion-breaker."""

        def init(params):
            zeros = lambda: jax.tree.map(jnp.zeros_like, params)
            return (jnp.ones((), jnp.float32), jnp.ones((), jnp.float32),
                    zeros(), zeros())

        def update(grads, state, params):
            b1pow, b2pow, m, v = state
            b1pow = b1pow * b1
            b2pow = b2pow * b2
            m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, m, grads)
            v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, v,
                             grads)
            rbc2 = jnp.sqrt(1 - b2pow)
            alpha = lr * rbc2 / (1 - b1pow)

            def upd(p, m_, v_):
                return p * (1 - lr * wd) - alpha * m_ / (
                    jnp.sqrt(v_) + eps * rbc2)

            return jax.tree.map(upd, params, m, v), (b1pow, b2pow, m, v)

        return Optimizer(init, update)

    def sgd_counted(lr, momentum=0.5) -> Optimizer:
        """Isolation probe: sgd(momentum) plus an unused 0-d step counter in
        the state — does a bare scalar in the scan carry trigger the
        blowup?"""

        def init(params):
            return (jnp.zeros((), jnp.int32),
                    jax.tree.map(jnp.zeros_like, params))

        def update(grads, state, params):
            count, buf = state
            buf = jax.tree.map(lambda b, g: momentum * b + g, buf, grads)
            new_params = jax.tree.map(lambda p, b: p - lr * b, params, buf)
            return new_params, (count + 1, buf)

        return Optimizer(init, update)

    def sgd_counted_used(lr, momentum=0.5) -> Optimizer:
        """Isolation probe: like sgd_counted but the update MULTIPLIES by a
        counter-derived traced scalar (constant-1 by construction) — does a
        scalar-dependent elementwise kernel trigger the blowup?"""

        def init(params):
            return (jnp.zeros((), jnp.int32),
                    jax.tree.map(jnp.zeros_like, params))

        def update(grads, state, params):
            count, buf = state
            count = count + 1
            scale = jnp.where(count > 0, 1.0, 0.5)   # traced, always 1.0
            buf = jax.tree.map(lambda b, g: momentum * b + g, buf, grads)
            new_params = jax.tree.map(lambda p, b: p - (lr * scale) * b,
                                      params, buf)
            return new_params, (count, buf)

        return Optimizer(init, update)

    def adamw_nobias_wd(lr, eps=1e-8, wd=0.01) -> Optimizer:
        """Isolation probe: adamw_nobias + decoupled weight decay with
        CONSTANT multiplier."""
        inner = adamw_nobias(lr, eps=eps)

        def update(grads, state, params):
            params = jax.tree.map(lambda p: p * (1 - lr * wd), params)
            return inner.update(grads, state, params)

        return Optimizer(inner.init, update)

    def adamw_eps_traced(lr, eps=1e-8) -> Optimizer:
        """Isolation probe: adamw_nobias but the denominator eps is a
        TRACED scalar carried in the state (constant-valued)."""

        def init(params):
            zeros = lambda: jax.tree.map(jnp.zeros_like, params)
            return (jnp.float32(eps), zeros(), zeros())

        def update(grads, state, params):
            eps_t, m, v = state
            m = jax.tree.map(lambda m_, g: 0.9 * m_ + 0.1 * g, m, grads)
            v = jax.tree.map(lambda v_, g: 0.999 * v_ + 0.001 * g * g, v,
                             grads)
            new_params = jax.tree.map(
                lambda p, m_, v_: p - lr * m_ / (jnp.sqrt(v_) + eps_t),
                params, m, v)
            return new_params, (eps_t, m, v)

        return Optimizer(init, update)

    def adamw_mulform(lr, b1=0.9, b2=0.999, eps=1e-8, wd=0.01) -> Optimizer:
        """Candidate fix: torch-identical AdamW where every traced
        bias-correction enters as a MULTIPLY and eps stays a CONSTANT add —
        update = p*(1-lr*wd) - (lr/bc1)*m / (sqrt(v*(1/bc2)) + eps), which
        is exactly torch's m_hat / (sqrt(v_hat) + eps) form."""

        def init(params):
            zeros = lambda: jax.tree.map(jnp.zeros_like, params)
            return (jnp.zeros((), jnp.int32), zeros(), zeros())

        def update(grads, state, params):
            step, m, v = state
            step = step + 1
            t = step.astype(jnp.float32)
            m = jax.tree.map(lambda m_, g: b1 * m_ + (1 - b1) * g, m, grads)
            v = jax.tree.map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, v,
                             grads)
            scaled_lr = lr / (1 - b1 ** t)        # scalar ops only
            inv_bc2 = 1.0 / (1 - b2 ** t)

            def upd(p, m_, v_):
                denom = jnp.sqrt(v_ * inv_bc2) + eps
                return p * (1 - lr * wd) - (scaled_lr * m_) / denom

            return jax.tree.map(upd, params, m, v), (step, m, v)

        return Optimizer(init, update)

    rows = {}
    # Default: just the sgd-vs-adamw fast-path comparison that regression-
    # guards the gate fix. The update-formula rewrites (nobias/eps_traced/
    # mulform/folded/...) were diagnostic probes for the round-5 packed-path
    # investigation; it concluded the blowup tracked the state-shape gate,
    # not the arithmetic (BASELINE.md), so they are retired to OPT_COST_FULL.
    variants = (("sgd", sgd(0.1, momentum=0.5)),
                ("adamw", adamw(1e-3)))
    if os.environ.get("OPT_COST_FULL"):
        variants = variants + (
            ("adamw_nobias", adamw_nobias(1e-3)),
            ("adamw_nobias_wd", adamw_nobias_wd(1e-3)),
            ("adamw_eps_traced", adamw_eps_traced(1e-3)),
            ("adamw_mulform", adamw_mulform(1e-3)),
            ("two_buffer_sgd", two_buffer_sgd(0.1)),
            ("adamw_running", adamw_running(1e-3)),
            ("sgd_counted", sgd_counted(0.1)),
            ("sgd_counted_used", sgd_counted_used(0.1)),
            ("adamw_folded", adamw_folded(1e-3)),
            ("adamw_bf16state", adamw_bf16state(1e-3)))

    hlo_dir = os.environ.get("OPT_COST_HLO_DIR")
    for name, opt in variants:
        opt_state = opt.init(buf)
        step = make_scanned_train_step(pipe, opt, pool_steps=steps)
        lowered = step.lower(buf, opt_state, xs, ts, key)
        compiled = lowered.compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, list):          # older jax returns [dict]
            cost = cost[0]
        mem = compiled.memory_analysis()
        row = {
            "optimizer": name,
            "flops_per_window": cost.get("flops"),
            "bytes_accessed_per_window": cost.get("bytes accessed"),
            "temp_bytes": getattr(mem, "temp_size_in_bytes", None),
            "argument_bytes": getattr(mem, "argument_size_in_bytes", None),
            "output_bytes": getattr(mem, "output_size_in_bytes", None),
        }
        rows[name] = row
        print(json.dumps(row))
        if hlo_dir:
            os.makedirs(hlo_dir, exist_ok=True)
            with open(os.path.join(hlo_dir, f"{name}.hlo.txt"), "w") as f:
                f.write(compiled.as_text())

    ref = rows.get("sgd") or rows.get("adamw_nobias")
    a = rows.get("adamw")
    if ref and a:
        verdict = {
            "reference": ref["optimizer"],
            "flops_ratio_adamw_over_ref":
                round(a["flops_per_window"] / ref["flops_per_window"], 3)
                if ref.get("flops_per_window") else None,
            "bytes_ratio_adamw_over_ref":
                round(a["bytes_accessed_per_window"]
                      / ref["bytes_accessed_per_window"], 3)
                if ref.get("bytes_accessed_per_window") else None,
            "temp_ratio_adamw_over_ref":
                round(a["temp_bytes"] / ref["temp_bytes"], 3)
                if ref.get("temp_bytes") else None,
        }
        print(json.dumps({"verdict": verdict}))


if __name__ == "__main__":
    main()
