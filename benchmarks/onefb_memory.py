"""Compiled-memory comparison of the two pipeline schedules.

The 1F1B schedule's reason to exist is that activation memory stays flat in
the microbatch count M while GPipe's grows linearly (its autodiff keeps all
M microbatches' residuals alive between the forward and backward sweeps).
This harness records XLA's own memory analysis (temp allocation bytes of
the compiled loss+grads program) for both schedules over a sweep of M —
hardware-independent evidence (the analysis is of the compiled program, not
a runtime measurement), runnable on the virtual-CPU mesh.

Prints one JSON line per (schedule, M) and writes
``benchmarks/onefb_memory.json``.
"""

from __future__ import annotations

import json
import os

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "onefb_memory.json")


def temp_bytes(schedule: str, m: int) -> int:
    """Temp allocation of the compiled loss+grads program for one schedule
    at ``m`` microbatches. The SAME helper backs both this benchmark and
    tests/test_onefb.py's memory-scaling assertion, so the recorded
    artifact and the CI guarantee can never measure different programs.
    Requires an initialized jax (any backend; the test and main() both use
    the 8-virtual-device CPU mesh)."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import jax

    from simple_distributed_machine_learning_tpu.models.mlp import (
        make_mlp_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )

    stages, wire, out = make_mlp_stages(jax.random.key(0), [256, 256, 10], 2)
    mesh = make_mesh(n_stages=2, n_data=1)
    p = Pipeline(stages, mesh, wire, out, n_microbatches=m,
                 schedule=schedule)
    x = jax.random.normal(jax.random.key(1), (16 * m, 256))
    y = jax.random.randint(jax.random.key(2), (16 * m,), 0, 10)
    buf = p.init_params()
    f = jax.jit(lambda b: p.loss_and_grads(b, x, y, jax.random.key(3),
                                           deterministic=True))
    return int(f.lower(buf).compile().memory_analysis().temp_size_in_bytes)


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", 8)
    except RuntimeError:
        pass

    rows = []
    for m in (1, 4, 16, 64):
        for sched in ("gpipe", "1f1b"):
            row = {"schedule": sched, "microbatches": m,
                   "temp_bytes": temp_bytes(sched, m)}
            rows.append(row)
            print(json.dumps(row))
    with open(OUT, "w") as f:
        json.dump(rows, f, indent=2)


if __name__ == "__main__":
    main()
