"""Distill the flash measurement artifacts into the default-policy decision.

Reads benchmarks/flash_timing.json (fixed-block rows + the jaxref ceiling
column) and benchmarks/flash_tune.log (block-sweep JSON lines) and prints:
per (T, dh): dense ms, best flash (blocks, ms, speedup), jaxref speedup.
Exit status: 0 if any flash row reaches >= 1.0x dense, 3 otherwise — the
"win exists / keep dense default" bit (BASELINE.md flash policy).

CPU-safe: reads artifacts only, never creates a device client.
"""

from __future__ import annotations

import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))


def main() -> int:
    points: dict[tuple[int, int], dict] = {}

    path = os.path.join(HERE, "flash_timing.json")
    if os.path.exists(path):
        with open(path) as f:
            for r in json.load(f):
                if "flash_ms" not in r:
                    continue
                p = points.setdefault((r["t"], r["dh"], r["dtype"]),
                                      {"cands": []})
                if r.get("dense_ms") is not None:
                    p["dense_ms"] = r["dense_ms"]
                p["cands"].append(("128/128(timing)", r["flash_ms"]))
                if r.get("jaxref_ms") is not None:
                    p["jaxref_ms"] = r["jaxref_ms"]

    path = os.path.join(HERE, "flash_tune.log")
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if "flash_ms" not in r:
                    continue
                p = points.setdefault((r["t"], r["dh"], "bfloat16"),
                                      {"cands": []})
                if r.get("dense_ms") is not None:
                    p.setdefault("dense_ms", r["dense_ms"])
                p["cands"].append((f"{r['bq']}/{r['bk']}", r["flash_ms"]))

    if not points:
        print("no flash artifacts found")
        return 3

    any_win = False
    print(f"{'T':>6} {'dh':>4} {'dtype':>9} {'dense ms':>9} "
          f"{'best flash':>16} {'speedup':>8} {'jaxref x':>9}")
    for (t, dh, dtype), p in sorted(points.items()):
        blocks, ms = min(p["cands"], key=lambda c: c[1])
        dense = p.get("dense_ms")
        speed = dense / ms if dense else None
        jref = (dense / p["jaxref_ms"]
                if dense and p.get("jaxref_ms") else None)
        if speed is not None and speed >= 1.0:
            any_win = True
        print(f"{t:>6} {dh:>4} {dtype:>9} "
              f"{dense if dense is not None else '--':>9} "
              f"{blocks + ' ' + format(ms, '.2f'):>16} "
              f"{format(speed, '.2f') if speed else '--':>8} "
              f"{format(jref, '.2f') if jref else '--':>9}")
    print("verdict:", "flash >= 1x exists" if any_win
          else "dense wins everywhere measured")
    return 0 if any_win else 3


if __name__ == "__main__":
    raise SystemExit(main())
