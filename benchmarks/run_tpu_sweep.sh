#!/usr/bin/env bash
# One-command TPU measurement sweep for a freshly healed axon tunnel.
#
# Discipline (see BASELINE.md incident notes): the tunnel serves ONE client
# at a time and a killed/overlapping client can wedge the server-side claim
# for hours. So: bounded smoke probe first, STRICTLY sequential clients,
# a settle pause between client exits, and never kill a client mid-dispatch
# (timeouts here are generous on purpose).
#
# Artifacts refreshed on success:
#   benchmarks/flash_timing.json   (dtype-fixed fwd+bwd kernels, dh=128/T=8192)
#   benchmarks/results_all.json    (all configs incl. AdamW bf16 rows + decode)
#   benchmarks/decode_timing.json  (KV-cache vs recompute tokens/sec)
#   flash_tune output              (benchmarks/flash_tune.log, block sweep)
set -u -o pipefail
cd "$(dirname "$0")/.."

probe() {
  timeout 90 python -c \
    "import jax, jax.numpy as jnp; print(float((jnp.ones((128,128))@jnp.ones((128,128))).sum()))" \
    >/dev/null 2>&1
}

echo "[sweep] probing tunnel..."
if ! probe; then
  echo "[sweep] tunnel wedged (probe timed out) - aborting before any client"
  exit 17
fi
sleep 20

echo "[sweep] 1/4 flash_timing (fwd+bwd, incl. dh=128 and T=8192 rows)"
timeout 2400 python benchmarks/flash_timing.py || echo "[sweep] flash_timing rc=$?"
sleep 60

echo "[sweep] 2/4 bench --all (all configs + decode row)"
timeout 3000 python bench.py --all || echo "[sweep] bench --all rc=$?"
sleep 60

echo "[sweep] 3/4 bench --config gpt_bf16_xl (MXU-stretch MFU row)"
timeout 1800 python bench.py --config gpt_bf16_xl || echo "[sweep] xl rc=$?"
sleep 60

echo "[sweep] 4/4 flash_tune block sweep (log: benchmarks/flash_tune.log)"
timeout 3000 python benchmarks/flash_tune.py | tee benchmarks/flash_tune.log \
  || echo "[sweep] flash_tune rc=$?"

echo "[sweep] done"
