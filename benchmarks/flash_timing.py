"""On-chip flash-vs-dense attention timing: fwd+bwd at long T.

Justifies the Pallas kernel (ops/flash_attention.py) with a measured number:
at T >= 1k the fused kernel beats XLA's dense causal attention (which
materializes the [T, T] score matrix in fwd AND bwd) on both time and HBM.

Prints one JSON line per (T, dtype) row:
    {"t": ..., "dtype": ..., "dense_ms": ..., "flash_ms": ..., "speedup": ...}
and writes benchmarks/flash_timing.json.

Each row also times jax.experimental.pallas.ops.tpu.flash_attention — the
hand-tuned reference TPU kernel — as ``jaxref_ms`` with
``jaxref_vs_dense = dense_ms / jaxref_ms``. That column is the ceiling
check: if the best-known public Pallas kernel ALSO trails XLA dense at a
given size on this chip, losing there is a property of the
(size, chip, compiler) point, not of our kernel.

Run on the TPU: python benchmarks/flash_timing.py
"""

from __future__ import annotations

import functools
import json
import math
import os
import time

import jax
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "flash_timing.json")

B, H = 4, 8
# (T, dh, dtype): dh=64 pays 2x lane padding on the MXU (the kernel pads the
# head dim to 128 lanes) — dh=128 rows show the kernel at its natural tile
ROWS = [(1024, 64, "float32"), (1024, 64, "bfloat16"),
        (2048, 64, "float32"), (2048, 64, "bfloat16"),
        (4096, 64, "bfloat16"),
        (2048, 128, "bfloat16"), (4096, 128, "bfloat16"),
        (8192, 128, "bfloat16")]
REPS = 20


def _dense_core(q, k, v):
    """XLA dense causal attention (the ops/attention.py math)."""
    from simple_distributed_machine_learning_tpu.ops.attention import (
        causal_attention_core,
    )
    return causal_attention_core(q, k, v)


def _time(fn, *args) -> float:
    """Best-of wall time for one compiled call, synced via block_until_ready
    + a forced host read (remote-tunnel-safe, like bench.py)."""
    out = fn(*args)                      # compile + warm
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(REPS):
            out = fn(*args)
        jax.block_until_ready(out)
        # force a host read of one element to close the tunnel round-trip
        float(jax.tree.leaves(out)[0].ravel()[0])
        best = min(best, (time.perf_counter() - t0) / REPS)
    return best * 1e3                    # ms


def main() -> None:
    import sys
    sys.path.insert(0, REPO)
    from simple_distributed_machine_learning_tpu.ops.flash_attention import (
        flash_attention,
    )

    rows = []
    for t, dh, dtype in ROWS:
        key = jax.random.key(0)
        kq, kk, kv = jax.random.split(key, 3)
        shape = (B, H, t, dh)
        dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
        q = jax.random.normal(kq, shape).astype(dt)
        k = jax.random.normal(kk, shape).astype(dt)
        v = jax.random.normal(kv, shape).astype(dt)

        def fwd_bwd(attn, q, k, v):
            def loss(q, k, v):
                return jnp.sum(attn(q, k, v).astype(jnp.float32) ** 2)
            l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
            return l, g

        dense = jax.jit(functools.partial(fwd_bwd, _dense_core))
        flash = jax.jit(functools.partial(fwd_bwd, flash_attention))

        lf, gf = flash(q, k, v)
        try:
            # parity first: the timing is meaningless if the values diverge
            ld, gd = dense(q, k, v)
            rel = abs(float(ld) - float(lf)) / max(abs(float(ld)), 1e-9)
            assert rel < (5e-2 if dtype == "bfloat16" else 1e-3), \
                f"T={t} {dtype}: loss mismatch {float(ld)} vs {float(lf)}"
            dense_ms = _time(dense, q, k, v)
        except Exception as e:  # noqa: BLE001 - dense OOM at long T is the
            # flash kernel's memory win, record it instead of dying
            if "RESOURCE_EXHAUSTED" not in str(e) and "memory" not in str(e).lower():
                raise
            dense_ms = None
        flash_ms = _time(flash, q, k, v)
        jaxref_ms = None
        try:
            from jax.experimental.pallas.ops.tpu.flash_attention import (
                flash_attention as jaxref_attn,
            )
            scale = 1.0 / math.sqrt(dh)
            ref = jax.jit(functools.partial(
                fwd_bwd,
                functools.partial(jaxref_attn, causal=True, sm_scale=scale)))
            lr_, _ = ref(q, k, v)
            rel = abs(float(lr_) - float(lf)) / max(abs(float(lf)), 1e-9)
            assert rel < (5e-2 if dtype == "bfloat16" else 1e-3), \
                f"T={t} {dtype}: jaxref loss mismatch {float(lr_)} vs {float(lf)}"
            jaxref_ms = _time(ref, q, k, v)
        except Exception as e:  # noqa: BLE001 - reference kernel is advisory:
            # an unsupported (size, dtype) point must not kill the sweep
            print(json.dumps({"t": t, "dtype": dtype, "dh": dh,
                              "jaxref_error": str(e)[:200]}))
        # dense_ms stays numeric-or-null (a string "OOM" broke consumers);
        # dense_oom carries the OOM fact separately
        row = {"t": t, "dtype": dtype, "b": B, "h": H, "dh": dh,
               "dense_ms": (round(dense_ms, 3) if dense_ms is not None
                            else None),
               "dense_oom": dense_ms is None,
               "flash_ms": round(flash_ms, 3),
               "speedup": (round(dense_ms / flash_ms, 2)
                           if dense_ms is not None else None),
               "jaxref_ms": (round(jaxref_ms, 3) if jaxref_ms is not None
                             else None),
               "jaxref_vs_dense": (round(dense_ms / jaxref_ms, 2)
                                   if dense_ms is not None
                                   and jaxref_ms is not None else None),
               "device": jax.devices()[0].device_kind}
        rows.append(row)
        print(json.dumps(row))
        # rewrite after every row: a late-row failure or a step timeout on
        # flaky hardware must not cost the rows already measured
        with open(OUT, "w") as f:
            json.dump(rows, f, indent=2)


if __name__ == "__main__":
    main()
