#!/usr/bin/env bash
# Round-5 remaining on-chip measurements, in value order, with the strict
# single-client discipline (see BASELINE.md incident notes): bounded smoke
# probe first, strictly sequential clients, 60 s settle + re-probe between
# clients, generous timeouts, never kill a client mid-dispatch.
#
# Steps (value order):
#   1. bench --all (AdamW-fixed bf16 rows + fixed decode harness)
#                                     -> benchmarks/results_all.json,
#                                        benchmarks/decode_timing.json
#   2. bench --config gpt_bf16_xl     -> MXU-stretch MFU row
#   3. flash_timing (jaxref column)   -> benchmarks/flash_timing.json
#   4. flash_tune block sweep         -> benchmarks/flash_tune.log
#   5. whole-model flash row          -> gpt_bf16 --attn flash
set -u -o pipefail
cd "$(dirname "$0")/.."

probe() {
  timeout 90 python -c \
    "import jax, jax.numpy as jnp; print(float((jnp.ones((128,128))@jnp.ones((128,128))).sum()))" \
    >/dev/null 2>&1
}

# Patient acquisition: after ANY client exits (including our own probes) the
# server can take minutes to re-grant the claim, so a single failed probe is
# not a wedge verdict — and a hard wedge (SIGTERM'd client mid-dispatch) has
# only ever cleared by server-side expiry ~20 h later. Probe every 15 minutes
# (sparse, in case killed-at-acquisition probes themselves reset the claim
# timer) up to a 10 h deadline.
deadline=$(( $(date +%s) + 10*3600 ))
n=0
while true; do
  n=$((n+1))
  echo "[r5] probe #$n $(date -u +%H:%M:%S)"
  if probe; then
    echo "[r5] tunnel ALIVE at $(date -u +%H:%M:%S) - starting sweep"
    break
  fi
  if [ "$(date +%s)" -ge "$deadline" ]; then
    echo "[r5] 10h deadline reached, tunnel never answered - giving up"
    exit 17
  fi
  sleep 900
done
sleep 60

settle_probe() {
  sleep 60
  for i in 1 2 3; do
    if probe; then sleep 30; return 0; fi
    echo "[r5] inter-step probe $i/3 failed $(date -u +%H:%M:%S)"
    sleep 120
  done
  echo "[r5] tunnel wedged between steps - aborting remaining steps"
  exit 17
}

# Ordering: the known-good artifact refreshes run FIRST; the compile-heavy
# flash_tune sweep runs LAST with the most generous timeout, because a
# timeout SIGTERM mid-dispatch can wedge the tunnel for hours (BASELINE.md)
# and must not take the core artifacts down with it.
echo "[r5] 1/5 bench --all (AdamW-fixed rows + decode) $(date -u +%H:%M:%S)"
timeout 3000 python bench.py --all || echo "[r5] bench --all rc=$?"
settle_probe

echo "[r5] 2/5 bench --config gpt_bf16_xl $(date -u +%H:%M:%S)"
timeout 1800 python bench.py --config gpt_bf16_xl || echo "[r5] xl rc=$?"
settle_probe

echo "[r5] 3/5 flash_timing (incl. jaxref column) $(date -u +%H:%M:%S)"
timeout 2400 python benchmarks/flash_timing.py || echo "[r5] flash_timing rc=$?"
settle_probe

echo "[r5] 4/5 flash_tune block sweep $(date -u +%H:%M:%S)"
timeout 4800 python benchmarks/flash_tune.py > benchmarks/flash_tune.log 2>&1 \
  || echo "[r5] flash_tune rc=$?"
tail -5 benchmarks/flash_tune.log
settle_probe

echo "[r5] 5/5 whole-model flash row: gpt_bf16 --attn flash $(date -u +%H:%M:%S)"
timeout 1800 python bench.py --config gpt_bf16 --attn flash \
  || echo "[r5] flash row rc=$?"

echo "[r5] done $(date -u +%H:%M:%S)"
