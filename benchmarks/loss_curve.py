"""The north-star loss-curve comparison (BASELINE.json: "match the
CPU-backend loss curve").

Runs the REFERENCE semantics — LeNet, MNIST/10 (6000 train / 1000 test),
batch 60, SGD(lr=0.1, momentum=0.5), 10 epochs, fixed batch order
(``/root/reference/simple_distributed.py:86-136``) — twice from the SAME
torch-default initial weights:

- torch: the reference's model/loop math, single process (the RPC split
  does not change the numerics — tests/test_multiprocess.py covers the
  process topology separately);
- ours: the 2-stage pipeline engine on a (stage=2) mesh, packed buffer,
  ppermute hops.

Dropout is OFF on both sides (SURVEY §6 parity caveat: train-time dropout
is stochastic and framework RNGs differ by construction; the reference
additionally has the worker-eval-dropout bug SURVEY §3.5 tells us not to
carry over).

Prints one JSON line per epoch per side and writes
benchmarks/loss_curves.json; BASELINE.md quotes the result.

Run (CPU is fine; this is a numerics check, not a perf check):
    python benchmarks/loss_curve.py
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

# force CPU through the live config: this container's sitecustomize imports
# jax at interpreter startup, which latches the platform (axon/TPU) before
# the env var is read — and a numerics run must not squat on the TPU chip
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))
OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "loss_curves.json")

EPOCHS = 10
BATCH = 60
LR, MOMENTUM = 0.1, 0.5

# NOTE on the reference hyperparameters: lr=0.1/momentum=0.5 is tuned for
# real MNIST (which needs network access this environment doesn't have). On
# the synthetic fallback task BOTH frameworks learn for ~1.5 epochs and then
# collapse to the uniform predictor IN LOCKSTEP (identical 2.3026 plateaus,
# max rel diff <3%) — trajectory parity through a divergence is still
# parity, but a second run at --lr 0.01 records a healthy learning curve.


def _data():
    from simple_distributed_machine_learning_tpu.data.mnist import load_mnist
    return load_mnist(os.path.join(REPO, "data"))   # synthetic fallback ok


def run_torch(train_ds, test_ds, lr=LR) -> dict:
    import torch
    import torch.nn.functional as F

    from test_torch_parity import _torch_forward, _torch_lenet

    m = _torch_lenet()
    params = [p for mod in m.values() for p in mod.parameters()]
    opt = torch.optim.SGD(params, lr=lr, momentum=MOMENTUM)

    def to_torch(x):        # NHWC -> NCHW
        return torch.from_numpy(np.ascontiguousarray(
            x.transpose(0, 3, 1, 2)))

    epochs = []
    n_train = len(train_ds.x)
    for epoch in range(1, EPOCHS + 1):
        tot, nb = 0.0, 0
        for s in range(0, n_train, BATCH):
            x = to_torch(train_ds.x[s:s + BATCH])
            y = torch.from_numpy(train_ds.y[s:s + BATCH].astype(np.int64))
            opt.zero_grad()
            loss = F.nll_loss(_torch_forward(m, x), y)
            loss.backward()
            opt.step()
            tot += float(loss)
            nb += 1
        with torch.no_grad():
            logp = _torch_forward(m, to_torch(test_ds.x))
            y = torch.from_numpy(test_ds.y.astype(np.int64))
            test_loss = float(F.nll_loss(logp, y, reduction="sum")) / len(y)
            acc = int((logp.argmax(1) == y).sum())
        row = {"side": "torch", "epoch": epoch,
               "train_loss": round(tot / nb, 6),
               "test_loss": round(test_loss, 6),
               "test_acc": acc, "n_test": len(y)}
        epochs.append(row)
        print(json.dumps(row))
    return {"epochs": epochs}


def run_ours(train_ds, test_ds, lr=LR) -> dict:
    import jax

    from test_torch_parity import _export_torch_params, _torch_lenet

    from simple_distributed_machine_learning_tpu.models.lenet import (
        FEATURES,
        IN_SHAPE,
        N_CLASSES,
        _conv_apply,
        _fc_apply,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
        Stage,
    )
    from simple_distributed_machine_learning_tpu.train.optimizer import sgd
    from simple_distributed_machine_learning_tpu.train.step import (
        make_eval_step,
    )

    conv_params, fc_params = _export_torch_params(_torch_lenet())
    stages = [
        Stage(apply=_conv_apply, params=conv_params, in_shape=IN_SHAPE),
        Stage(apply=_fc_apply, params=fc_params, in_shape=(FEATURES,)),
    ]
    n_dev = len(jax.devices())
    n_stages = 2 if n_dev >= 2 else 1
    if n_stages == 1:       # single device: fuse the two stages
        def fused(params, x, key, deterministic):
            h = _conv_apply(params["conv"], x, key, deterministic)
            return _fc_apply(params["fc"], h, key, deterministic)
        stages = [Stage(apply=fused,
                        params={"conv": conv_params, "fc": fc_params},
                        in_shape=IN_SHAPE)]
    mesh = make_mesh(n_stages=n_stages, n_data=1)
    pipe = Pipeline(stages, mesh, 28 * 28, N_CLASSES)
    opt = sgd(lr, MOMENTUM)
    buf = pipe.init_params()
    state = opt.init(buf)

    @jax.jit
    def step(buf, state, x, t):
        def loss_fn(b):
            # deterministic=True: dropout off, matching the torch side
            return pipe.loss_and_logits(b, x, t, jax.random.key(0),
                                        deterministic=True)[0]
        loss, grads = jax.value_and_grad(loss_fn)(buf)
        buf, state = opt.update(grads, state, buf)
        return buf, state, loss

    eval_step = make_eval_step(pipe)
    epochs = []
    n_train = len(train_ds.x)
    for epoch in range(1, EPOCHS + 1):
        tot, nb = 0.0, 0
        for s in range(0, n_train, BATCH):
            x = train_ds.x[s:s + BATCH]
            y = train_ds.y[s:s + BATCH].astype(np.int32)
            buf, state, loss = step(buf, state, x, y)
            tot += float(loss)
            nb += 1
        sum_nll, correct = 0.0, 0
        n_test = len(test_ds.x)
        for s in range(0, n_test, BATCH):
            x = test_ds.x[s:s + BATCH]
            y = test_ds.y[s:s + BATCH].astype(np.int32)
            sl, c = eval_step(buf, x, y, jax.random.key(0),
                              np.int32(len(x)))
            sum_nll += float(sl)
            correct += int(c)
        row = {"side": "ours", "epoch": epoch,
               "train_loss": round(tot / nb, 6),
               "test_loss": round(sum_nll / n_test, 6),
               "test_acc": correct, "n_test": n_test}
        epochs.append(row)
        print(json.dumps(row))
    return {"epochs": epochs}


def main() -> None:
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--lr", type=float, default=LR)
    ap.add_argument("--out", type=str, default=OUT)
    args = ap.parse_args()
    train_ds, test_ds = _data()
    ours = run_ours(train_ds, test_ds, lr=args.lr)
    torch_res = run_torch(train_ds, test_ds, lr=args.lr)
    rows = {"config": {"epochs": EPOCHS, "batch": BATCH, "lr": args.lr,
                       "momentum": MOMENTUM, "n_train": len(train_ds.x),
                       "n_test": len(test_ds.x), "dropout": "off (SURVEY §6)"},
            "ours": ours["epochs"], "torch": torch_res["epochs"]}
    # the comparison the files exist for: per-epoch curve agreement
    max_rel = max(
        abs(a["train_loss"] - b["train_loss"])
        / max(abs(b["train_loss"]), 1e-9)
        for a, b in zip(rows["ours"], rows["torch"]))
    rows["max_train_loss_rel_diff"] = round(max_rel, 6)
    rows["final_acc_ours"] = rows["ours"][-1]["test_acc"]
    rows["final_acc_torch"] = rows["torch"][-1]["test_acc"]
    with open(args.out, "w") as f:
        json.dump(rows, f, indent=2)
    print(json.dumps({"max_train_loss_rel_diff": rows["max_train_loss_rel_diff"],
                      "final_acc_ours": rows["final_acc_ours"],
                      "final_acc_torch": rows["final_acc_torch"]}))


if __name__ == "__main__":
    main()
