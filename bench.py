"""Headline benchmark: samples/sec/chip on the 2-stage MLP pipeline.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Config (BASELINE.json configs 1-2): 2-layer MLP 784-512-10 (stage0=fc1,
stage1=fc2), batch 60 (the reference's batch size, simple_distributed.py:18),
SGD(lr=0.1, momentum=0.5), random tensors. The measured run uses the
epoch-compiled train step (lax.scan over batches) — one dispatch per window,
so the number reflects chip throughput, not host/tunnel dispatch latency.

``vs_baseline`` divides by the stored CPU baseline (benchmarks/
baseline_cpu.json): the torch.distributed.rpc 2-process CPU implementation of
the same workload (the reference's architecture, measured by
benchmarks/torch_rpc_baseline.py) — i.e. "ours on TPU vs theirs on CPU",
which is the north-star comparison. Regenerate baselines with
``python bench.py --measure-baseline``.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_PATH = os.path.join(REPO, "benchmarks", "baseline_cpu.json")

DIMS = [784, 512, 10]
BATCH = 60
N_MICRO = 1          # reference schedule: one microbatch
# steps per compiled scan window: large enough that one window is tens of ms
# of chip time — per-dispatch latency (ms-scale through a remote-chip tunnel)
# must not dominate the measurement
SCAN_STEPS = 5000
WINDOWS = 5


def measure_pipeline_sps(scan_steps: int = SCAN_STEPS,
                         windows: int = WINDOWS) -> dict:
    import jax
    import jax.numpy as jnp

    from simple_distributed_machine_learning_tpu.models.mlp import make_mlp_stages
    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import Pipeline
    from simple_distributed_machine_learning_tpu.train.optimizer import sgd
    from simple_distributed_machine_learning_tpu.train.step import (
        make_scanned_train_step,
    )

    n_dev = len(jax.devices())
    n_stages = 2 if n_dev >= 2 else 1
    mesh = make_mesh(n_stages=n_stages, n_data=1)

    key = jax.random.key(0)
    stages, wire_dim, out_dim = make_mlp_stages(key, DIMS, n_stages)
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=N_MICRO)
    buf = pipe.init_params()
    opt = sgd(0.1, momentum=0.5)
    opt_state = opt.init(buf)
    step = make_scanned_train_step(pipe, opt)

    # Two-point measurement: time ONE dispatch of the compiled N-step window
    # vs TWO back-to-back dispatches (the second chains on the first through
    # the donated buffers), each closed with a FORCED host read of the final
    # loss — block_until_ready alone does not reliably block on remote-tunnel
    # backends. The difference cancels every fixed cost (dispatch, tunnel
    # round-trip, the host read) and leaves pure chip time for N steps, with
    # one compilation and one input buffer.
    xs = jax.random.normal(key, (scan_steps, BATCH, DIMS[0]))
    ts = jax.random.randint(key, (scan_steps, BATCH), 0, DIMS[-1])
    jax.block_until_ready((xs, ts))

    def timed(reps, buf, opt_state):
        t0 = time.perf_counter()
        for r in range(reps):
            buf, opt_state, losses = step(buf, opt_state, xs, ts,
                                          jax.random.fold_in(key, r))
        final_loss = float(losses[-1])            # forced device->host sync
        return time.perf_counter() - t0, final_loss, buf, opt_state

    _, _, buf, opt_state = timed(1, buf, opt_state)          # compile + warm
    t1 = t2 = float("inf")
    for _ in range(windows):
        dt, final_loss, buf, opt_state = timed(1, buf, opt_state)
        t1 = min(t1, dt)
        dt, final_loss, buf, opt_state = timed(2, buf, opt_state)
        t2 = min(t2, dt)
    if t2 - t1 <= 0:
        raise RuntimeError(
            f"two-point timing collapsed (t1={t1:.4f}s, t2={t2:.4f}s): "
            f"dispatch noise exceeds one {scan_steps}-step window of chip "
            f"time — raise --steps")
    best = scan_steps * BATCH / (t2 - t1)

    n_chips = n_stages  # chips participating in the pipeline
    return {
        "samples_per_sec": best,
        "samples_per_sec_per_chip": best / n_chips,
        "n_chips": n_chips,
        "backend": jax.default_backend(),
        "final_loss": final_loss,
    }


def _measure_jax_cpu_baseline() -> float:
    """Our own pipeline on 2 virtual CPU devices (BASELINE config 1 analog)."""
    code = (
        "import os; os.environ['JAX_PLATFORMS']='cpu';"
        "import jax; jax.config.update('jax_platforms','cpu');"
        "jax.config.update('jax_num_cpu_devices',2);"
        "import sys; sys.path.insert(0, %r);"
        "from bench import measure_pipeline_sps;"
        "import json; print('RESULT'+json.dumps(measure_pipeline_sps()))"
        % REPO)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=600, cwd=REPO)
    for line in out.stdout.splitlines():
        if line.startswith("RESULT"):
            return json.loads(line[len("RESULT"):])["samples_per_sec"]
    raise RuntimeError(f"jax cpu baseline failed: {out.stderr[-2000:]}")


def _measure_torch_rpc_baseline() -> float:
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "torch_rpc_baseline.py")],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    for line in out.stdout.splitlines():
        if line.startswith("RESULT"):
            return json.loads(line[len("RESULT"):])["samples_per_sec"]
    raise RuntimeError(f"torch rpc baseline failed: {out.stderr[-2000:]}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--measure-baseline", action="store_true",
                    help="re-measure CPU baselines and rewrite "
                         "benchmarks/baseline_cpu.json")
    ap.add_argument("--steps", type=int, default=SCAN_STEPS)
    args = ap.parse_args()

    if args.measure_baseline or not os.path.exists(BASELINE_PATH):
        baselines = {}
        try:
            baselines["torch_rpc_cpu_samples_per_sec"] = \
                _measure_torch_rpc_baseline()
        except Exception as e:  # noqa: BLE001 - record and continue
            baselines["torch_rpc_cpu_error"] = str(e)[-500:]
        baselines["jax_cpu_pipeline_samples_per_sec"] = \
            _measure_jax_cpu_baseline()
        os.makedirs(os.path.dirname(BASELINE_PATH), exist_ok=True)
        with open(BASELINE_PATH, "w") as f:
            json.dump(baselines, f, indent=2)
    else:
        with open(BASELINE_PATH) as f:
            baselines = json.load(f)

    res = measure_pipeline_sps(scan_steps=args.steps)
    base = baselines.get("torch_rpc_cpu_samples_per_sec") or \
        baselines.get("jax_cpu_pipeline_samples_per_sec")
    print(json.dumps({
        "metric": "2stage_mlp_pipeline_samples_per_sec_per_chip",
        "value": round(res["samples_per_sec_per_chip"], 1),
        "unit": "samples/sec/chip",
        "vs_baseline": round(res["samples_per_sec"] / base, 2) if base else None,
    }))


if __name__ == "__main__":
    main()
