"""Benchmarks: samples/sec/chip + MFU for every BASELINE.json config.

Default invocation prints ONE JSON line (the headline config — the 2-stage
MLP of BASELINE.json configs 1-2):
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "mfu": ...}

``--all`` additionally measures the 4-stage MLP (config 3), LeNet (config 4),
the tiny GPipe GPT (config 5), and a bf16 GPT sized to load the MXU, printing
one JSON line per row and writing ``benchmarks/results_all.json``.

Measurement: the epoch-compiled train step (``lax.scan`` over batches) with a
small resident POOL of input batches (``pool_steps`` in
``train/step.py``) — one dispatch per window, so the number reflects chip
throughput, not host/tunnel dispatch latency, without pinning GBs of inputs.
Two-point timing (one window vs two back-to-back windows, each closed with a
forced host read) cancels every fixed cost: dispatch, tunnel round-trip, the
host read.

MFU: closed-form training FLOPs (fwd matmul FLOPs x3 — the standard
approximation; backward costs 2x forward) divided by the chip's peak. Peaks
are the published bf16 matmul numbers per device kind; f32 rows are still
divided by the bf16 peak (TPU MXUs execute f32 matmuls via bf16 passes at
default precision), so f32 MFU is an honest "fraction of the chip" figure.

``vs_baseline`` divides by the stored CPU baseline (benchmarks/
baseline_cpu.json): the torch.distributed.rpc 2-process CPU implementation of
the same workload (the reference's architecture, measured by
benchmarks/torch_rpc_baseline.py) — i.e. "ours on TPU vs theirs on CPU",
which is the north-star comparison (BASELINE.json config 1 vs 2). Regenerate
with ``python bench.py --measure-baseline``.

Single-chip note: with one device the pipeline degenerates to the fused
single-stage model (``Pipeline.loss_and_logits``'s fast path) — the same
math, no ppermute. The multi-stage shard_map engine is covered on virtual
CPU meshes (tests/) and by the driver's ``dryrun_multichip``; its on-chip
throughput needs >=2 real chips, which this environment does not have.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
BASELINE_PATH = os.path.join(REPO, "benchmarks", "baseline_cpu.json")
RESULTS_PATH = os.path.join(REPO, "benchmarks", "results_all.json")

# published peak bf16 matmul FLOP/s per chip, by jax device_kind
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,      # v5e
    "TPU v5e": 197e12,
    "TPU v4": 275e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,      # v6e / Trillium
}

POOL = 16                       # resident input batches per window


def _mlp_flops(dims):
    """Per-sample training FLOPs of an MLP: 3 x fwd, fwd = 2*sum(d_i*d_i+1)."""
    return 6 * sum(dims[i] * dims[i + 1] for i in range(len(dims) - 1))


def _lenet_flops():
    """LeNet per-sample training FLOPs (convs dominate; pools/bias dropped).

    conv1 1->10 k5 on 28x28 (out 24x24), conv2 10->20 k5 on 12x12 (out 8x8),
    fc 320->50->10 — the reference's exact architecture
    (/root/reference/simple_distributed.py:26-95).
    """
    conv1 = 2 * 24 * 24 * 10 * (5 * 5 * 1)
    conv2 = 2 * 8 * 8 * 20 * (5 * 5 * 10)
    fc = 2 * (320 * 50 + 50 * 10)
    return 3 * (conv1 + conv2 + fc)


def _gpt_flops(cfg):
    """Per-sample training FLOPs of the GPT (3 x fwd matmul FLOPs).

    Per token per layer: qkvo projections 8d^2, attention scores+values 4Td,
    MLP (ratio r) 2*2*r*d^2; head 2dV per token. Causal masking's 2x saving
    on the score matmuls is NOT credited (XLA computes the full product).
    """
    d, T, L, V, r = (cfg.d_model, cfg.seq_len, cfg.n_layers, cfg.vocab,
                     cfg.mlp_ratio)
    per_tok = L * (8 * d * d + 4 * T * d + 4 * r * d * d) + 2 * d * V
    return 3 * T * per_tok


def _build_mlp(dims, n_dev):
    import jax

    from simple_distributed_machine_learning_tpu.models.mlp import (
        make_mlp_stages,
    )
    want = len(dims) - 1
    # degrade gracefully: as many pipeline stages as there are devices
    # (still a real multi-stage pipeline on 2-3 chips, fused only on 1);
    # n_chips in the output row records what actually ran
    n_stages = want if n_dev >= want else (2 if n_dev >= 2 else 1)
    stages, wire_dim, out_dim = make_mlp_stages(jax.random.key(0), dims,
                                                n_stages)
    return stages, wire_dim, out_dim, n_stages


def _data_mlp(dims, batch, pool):
    import jax
    key = jax.random.key(1)
    xs = jax.random.normal(key, (pool, batch, dims[0]))
    ts = jax.random.randint(key, (pool, batch), 0, dims[-1])
    return xs, ts


def _data_img(batch, pool):
    import jax
    key = jax.random.key(1)
    xs = jax.random.normal(key, (pool, batch, 28, 28, 1))
    ts = jax.random.randint(key, (pool, batch), 0, 10)
    return xs, ts


def _data_gpt(cfg, batch, pool):
    import jax
    key = jax.random.key(1)
    xs = jax.random.randint(key, (pool, batch, cfg.seq_len), 0,
                            cfg.vocab).astype("float32")
    ts = jax.random.randint(jax.random.key(2), (pool, batch, cfg.seq_len), 0,
                            cfg.vocab)
    return xs, ts


def _configs():
    """name -> spec. Built lazily so jax only imports inside measure()."""
    from simple_distributed_machine_learning_tpu.models.gpt import GPTConfig

    mlp2 = [784, 512, 10]
    mlp4 = [784, 512, 512, 512, 10]
    tiny_gpt = GPTConfig(vocab=128, seq_len=64, d_model=128, n_heads=4,
                         n_layers=2)
    big_gpt = GPTConfig(vocab=8192, seq_len=256, d_model=512, n_heads=8,
                        n_layers=4)
    return {
        # BASELINE.json config 2 (headline; config 1 is the torch-RPC CPU
        # baseline of the same workload)
        # steps are sized so one compiled window is >= ~200 ms of chip time:
        # the axon tunnel's dispatch jitter is ~10 ms, so shorter windows
        # drown the signal (observed: a 25 ms window made MFU read >1.0)
        "mlp2": dict(kind="mlp", dims=mlp2, batch=60, n_micro=1,
                     steps=30000, flops=_mlp_flops(mlp2), dtype=None),
        # config 3: 4-layer MLP -> 4-stage pipeline, microbatch=1
        "mlp4": dict(kind="mlp", dims=mlp4, batch=60, n_micro=1,
                     steps=20000, flops=_mlp_flops(mlp4), dtype=None),
        # config 4: LeNet split conv<->fc (the reference's own workload)
        "lenet": dict(kind="lenet", batch=60, n_micro=1, steps=4000,
                      flops=_lenet_flops(), dtype=None),
        # config 5: 2-layer tiny-GPT (d=128) with GPipe microbatching
        "gpt": dict(kind="gpt", cfg=tiny_gpt, batch=32, n_micro=4,
                    steps=1000, flops=_gpt_flops(tiny_gpt), dtype=None),
        # MXU-sized bf16 GPT: the MFU row (not a BASELINE config; sized so
        # the matmuls are large enough for the systolic array to matter).
        # bf16 rows train with AdamW: SGD at the f32 rows' lr=0.1 diverges
        # to NaN in half precision (observed r4), and a NaN final_loss means
        # the throughput was measured on garbage values
        "gpt_bf16": dict(kind="gpt", cfg=big_gpt, batch=16, n_micro=1,
                         steps=100, flops=_gpt_flops(big_gpt),
                         dtype="bfloat16", opt="adamw"),
        "mlp2_bf16": dict(kind="mlp", dims=mlp2, batch=60, n_micro=1,
                          steps=15000, flops=_mlp_flops(mlp2),
                          dtype="bfloat16", opt="adamw"),
    }


def _xl_config():
    """MXU-stretch bf16 GPT (d=1024, T=512): not part of ``--all`` (slower
    compile + more HBM than the sweep budget wants); run explicitly with
    ``python bench.py --config gpt_bf16_xl`` to probe peak MFU."""
    from simple_distributed_machine_learning_tpu.models.gpt import GPTConfig

    xl = GPTConfig(vocab=8192, seq_len=512, d_model=1024, n_heads=16,
                   n_layers=4)
    return dict(kind="gpt", cfg=xl, batch=8, n_micro=1, steps=24,
                flops=_gpt_flops(xl), dtype="bfloat16", opt="adamw")


def _smoke_check(timeout_s: float = 90.0) -> None:
    """Fail fast with a diagnosis if the accelerator is unresponsive.

    A wedged remote-chip tunnel (e.g. a prior client killed mid-execution
    leaving its claim held server-side) blocks the first dispatch forever;
    without this check the whole bench silently hangs until the outer
    harness timeout with no clue in the output.
    """
    import threading

    import jax.numpy as jnp

    done = threading.Event()
    err: list[BaseException] = []

    def probe():
        try:
            jnp.ones((128, 128)).block_until_ready()
        except BaseException as e:  # noqa: BLE001 - re-raised in main thread
            err.append(e)
        finally:
            done.set()

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    if done.wait(timeout_s):
        if err:
            # the probe RAISED (plugin/init error) — that is not a hang;
            # surface the real exception instead of the wedged diagnosis
            raise err[0]
        return
    # NO jax calls here: with the device wedged even jax.default_backend()
    # blocks on the backend-init lock the probe thread is stuck holding
    sys.stderr.write(
        f"bench: accelerator unresponsive - a 128x128 constant did not "
        f"materialize within {timeout_s:.0f}s; the device/tunnel is "
        f"wedged (stale claim from a killed client?); no measurement "
        f"possible\n")
    sys.stderr.flush()
    # os._exit, not raise: with the device wedged, normal interpreter exit
    # hangs too (jax's atexit backend finalization blocks on the same dead
    # tunnel)
    os._exit(WEDGED_RC)


# the wedged-accelerator exit signature (ROADMAP standing note: BENCH
# r04/r05 recorded "accelerator unresponsive", rc 17, no measurement)
WEDGED_RC = 17


def _smoke_probe_main() -> None:
    """``bench.py --smoke-probe``: the probe SUBPROCESS body. Exits 0 when
    a small constant materializes, ``WEDGED_RC`` on the wedged signature.
    ``SDML_FAULT_WEDGE=1`` (set by the parent when a ``wedged-device``
    fault fires at the ``bench.probe`` site) simulates the wedge
    deterministically, so the retry/reporting path is testable on CPU."""
    if os.environ.get("SDML_FAULT_WEDGE"):
        sys.stderr.write(
            "bench: accelerator unresponsive - injected wedged-device "
            "fault (resilience/faults.py); simulating the rc-17 "
            "signature\n")
        sys.stderr.flush()
        os._exit(WEDGED_RC)
    _apply_env_platform()
    _smoke_check()
    print("bench: smoke probe ok")


def _probe_subprocess(attempt: int, timeout_s: float) -> int:
    """Run the smoke probe as a subprocess and return its exit code; a
    parent-side timeout (the child's own 90s watchdog failing to fire —
    e.g. wedged before Python even runs) maps onto the rc-17 signature.
    Consults the active fault plan at the ``bench.probe`` site so a
    scheduled ``wedged-device`` fault wedges exactly the attempts it
    names."""
    from simple_distributed_machine_learning_tpu.resilience.faults import (
        check as _check_fault,
    )
    env = dict(os.environ)
    if any(f.kind == "wedged-device"
           for f in _check_fault("bench.probe", step=attempt)):
        env["SDML_FAULT_WEDGE"] = "1"
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--smoke-probe"],
            env=env, cwd=REPO, timeout=timeout_s)
        return out.returncode
    except subprocess.TimeoutExpired:
        return WEDGED_RC


def _supervised_smoke(probe=_probe_subprocess, retries: int = 1,
                      backoff_s: float | None = None,
                      sleep=time.sleep) -> bool:
    """The rc-17-aware accelerator preflight: probe, retry once with
    backoff on the wedged signature (a stale tunnel claim can clear), and
    on persistent wedge EMIT A STRUCTURED ROW —
    ``{"metric": "device_unhealthy", ...}`` — instead of dying with no
    measurement (the r04/r05 failure mode). Returns False when the sweep
    should be skipped; non-wedge probe failures still exit nonzero (a
    broken install must stay loud)."""
    if backoff_s is None:
        backoff_s = float(os.environ.get("SDML_BENCH_PROBE_BACKOFF", "10"))
    timeout_s = float(os.environ.get("SDML_BENCH_PROBE_TIMEOUT", "150"))
    for attempt in range(retries + 1):
        rc = probe(attempt, timeout_s)
        if rc == 0:
            return True
        if rc != WEDGED_RC:
            sys.stderr.write(f"bench: smoke probe failed with rc={rc} "
                             f"(not the wedged-device signature) — "
                             f"aborting\n")
            sys.exit(rc or 1)
        if attempt < retries:
            sys.stderr.write(
                f"bench: accelerator unresponsive (rc-{WEDGED_RC} wedged "
                f"signature), attempt {attempt + 1}/{retries + 1} — "
                f"retrying in {backoff_s:.0f}s\n")
            sys.stderr.flush()
            sleep(backoff_s)
            backoff_s *= 2
    print(json.dumps({
        "metric": "device_unhealthy",
        "rc": WEDGED_RC,
        "attempts": retries + 1,
        "detail": "accelerator unresponsive (wedged device/tunnel); "
                  "no throughput measurement possible",
    }))
    return False


def measure(name: str, spec: dict, windows: int = 5,
            schedule: str = "gpipe", lint: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from simple_distributed_machine_learning_tpu.parallel.mesh import make_mesh
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )
    from simple_distributed_machine_learning_tpu.train.optimizer import (
        adamw,
        sgd,
    )
    from simple_distributed_machine_learning_tpu.train.step import (
        make_scanned_train_step,
    )

    n_dev = len(jax.devices())
    batch, n_micro = spec["batch"], spec["n_micro"]
    steps = spec.get("steps_override") or spec["steps"]

    if spec["kind"] == "mlp":
        stages, wire_dim, out_dim, n_stages = _build_mlp(spec["dims"], n_dev)
        xs, ts = _data_mlp(spec["dims"], batch, POOL)
    elif spec["kind"] == "lenet":
        from simple_distributed_machine_learning_tpu.models.lenet import (
            make_lenet_stages,
        )
        n_stages = 2 if n_dev >= 2 else 1
        stages, wire_dim, out_dim = make_lenet_stages(jax.random.key(0),
                                                      n_stages)
        xs, ts = _data_img(batch, POOL)
    else:
        from simple_distributed_machine_learning_tpu.models.gpt import (
            make_gpt_stages,
        )
        import dataclasses as _dc
        cfg = spec["cfg"]
        if spec.get("attn"):
            fb = spec.get("flash_blocks") or (128, 128)
            cfg = _dc.replace(cfg, attn_impl=spec["attn"],
                              flash_block_q=fb[0], flash_block_k=fb[1])
        tp = spec.get("tp") or 1
        if tp > 1 or spec.get("overlap"):
            # full spec validation through the analyzer preflight: device
            # count, head/hidden divisibility, and the ring-overlap chunk
            # counts — one clear message instead of a trace-time stack
            from simple_distributed_machine_learning_tpu.analysis.preflight import (
                validate_tp_overlap,
            )
            errors, warns = validate_tp_overlap(
                tp, spec.get("overlap") or "none", n_dev, cfg,
                batch=batch, n_micro=n_micro)
            for w in warns:
                sys.stderr.write(f"bench: {name}: {w}\n")
            if errors:
                raise SystemExit(f"bench: {name}: invalid --tp/--overlap "
                                 f"spec:\n  " + "\n  ".join(errors))
        if tp > 1:
            # the TP sweep measures the collective schedule, so the whole
            # mesh goes to the model axis (one stage). This also keeps the
            # ring's ppermutes out of divergent lax.switch branches, whose
            # global collective-permute rendezvous deadlocks on XLA:CPU
            # smoke runs (on TPU the permutes are independent ICI DMAs)
            cfg = _dc.replace(cfg, n_tensor_parallel=tp,
                              overlap=spec.get("overlap") or "none")
            n_stages = 1
        else:
            n_stages = 2 if n_dev >= 2 else 1
        stages, wire_dim, out_dim = make_gpt_stages(jax.random.key(0), cfg,
                                                    n_stages)
        xs, ts = _data_gpt(cfg, batch, POOL)

    n_model = (spec.get("tp") or 1) if spec["kind"] == "gpt" else 1
    mesh = make_mesh(n_stages=n_stages, n_data=1, n_model=n_model)
    dtype = jnp.bfloat16 if spec["dtype"] == "bfloat16" else None
    # 1f1b needs >= 2 stages; on a single chip the pipeline degenerates to
    # the fused path either way
    sched = schedule if n_stages >= 2 else "gpipe"
    pipe = Pipeline(stages, mesh, wire_dim, out_dim, n_microbatches=n_micro,
                    compute_dtype=dtype, schedule=sched,
                    overlap=spec.get("overlap") or "none")
    buf = pipe.init_params()
    lr = spec.get("lr")
    if spec.get("opt") == "adamw":
        opt = adamw(1e-3 if lr is None else lr)
    else:
        opt = sgd(0.1 if lr is None else lr, momentum=0.5)
    opt_state = opt.init(buf)
    step = make_scanned_train_step(pipe, opt, pool_steps=steps)
    key = jax.random.key(0)
    # abstract shapes of the exact step being timed, captured BEFORE any
    # donation: the static ICI-bytes gauge (telemetry/ici.py) traces on these
    from simple_distributed_machine_learning_tpu.analysis import abstractify
    step_sds = (abstractify(buf), abstractify(opt_state), abstractify(xs),
                abstractify(ts), abstractify(key))
    lint_report = None
    if lint:
        # preflight the EXACT scanned step about to be timed (same spec,
        # schedule, overlap, donation) — abstract trace only, no FLOPs
        from simple_distributed_machine_learning_tpu.analysis import analyze
        lint_report = analyze(step, *step_sds, mesh=mesh,
                              name=f"bench:{name}")
        print(lint_report.format(costs=True))
        if not lint_report.ok():
            raise SystemExit(2)
    jax.block_until_ready((xs, ts))

    def timed(reps, buf, opt_state):
        t0 = time.perf_counter()
        for r in range(reps):
            buf, opt_state, losses = step(buf, opt_state, xs, ts,
                                          jax.random.fold_in(key, r))
        final_loss = float(losses[-1])            # forced device->host sync
        return time.perf_counter() - t0, final_loss, buf, opt_state

    t_compile, _, buf, opt_state = timed(1, buf, opt_state)  # compile + warm
    # paired two-point windows: (3 dispatches - 1 dispatch)/2 cancels every
    # fixed cost (dispatch, tunnel round-trip, the host read) within the SAME
    # pair; the median over pairs rejects tunnel-jitter outliers (taking
    # separate mins of t1/t2 across windows is biased when jitter ~ window)
    #
    # every per-window estimate also feeds a StepTimer histogram so rows
    # report p50/p95/max per-step latency, not just the median-derived mean
    from simple_distributed_machine_learning_tpu.telemetry.timer import (
        StepTimer,
    )
    timer = StepTimer()
    timer.record_window(t_compile, steps=1)      # the compile window
    diffs = []
    for _ in range(windows):
        d1, final_loss, buf, opt_state = timed(1, buf, opt_state)
        d3, final_loss, buf, opt_state = timed(3, buf, opt_state)
        diffs.append((d3 - d1) / 2)
        if diffs[-1] > 0:                # negative = jitter swamped the pair
            timer.record_window(diffs[-1], steps=steps,
                                examples=steps * batch)
    diffs.sort()
    dt = diffs[len(diffs) // 2]
    if dt <= 0:
        raise RuntimeError(
            f"{name}: two-point timing collapsed (median diff {dt:.4f}s) - "
            f"dispatch noise exceeds one {steps}-step window; raise --steps")
    sps = steps * batch / dt

    kind = jax.devices()[0].device_kind
    peak = PEAK_FLOPS.get(kind)
    achieved = sps * spec["flops"]     # aggregate FLOP/s across the pipeline
    n_chips = n_stages * n_model

    # observability columns (telemetry/): per-step latency quantiles from
    # the window histogram, the schedule-model pipeline bubble, and the
    # statically expected collective bytes per step — bytes/step next to
    # ms/step. All additive keys: the row schema only ever grows.
    from simple_distributed_machine_learning_tpu.telemetry.bubble import (
        schedule_bubble_fraction,
    )
    from simple_distributed_machine_learning_tpu.telemetry.ici import (
        expected_ici_bytes,
        from_report,
    )
    tstats = timer.summary()
    # --lint already traced this exact step: reuse its cost table instead of
    # paying the jaxpr trace a second time
    ici_info = (from_report(lint_report, steps=steps) if lint_report is not None
                else expected_ici_bytes(step, *step_sds, mesh=mesh,
                                        name=f"bench:{name}", steps=steps))
    return {
        "config": name,
        "samples_per_sec": round(sps, 1),
        "samples_per_sec_per_chip": round(sps / n_chips, 1),
        "n_chips": n_chips,
        "dtype": spec["dtype"] or "float32",
        "flops_per_sample": spec["flops"],
        "achieved_tflops": round(achieved / 1e12, 2),
        # model-FLOPs utilization of the chips that ran: aggregate FLOP/s
        # over aggregate peak
        "mfu": round(achieved / (n_chips * peak), 4) if peak else None,
        "device_kind": kind,
        "backend": jax.default_backend(),
        "optimizer": spec.get("opt", "sgd"),
        "lr": (spec["lr"] if spec.get("lr") is not None
               else (1e-3 if spec.get("opt") == "adamw" else 0.1)),
        "schedule": sched,
        "attn": (spec.get("attn", "dense") if spec["kind"] == "gpt"
                 else None),
        "tp": (spec.get("tp") or 1) if spec["kind"] == "gpt" else None,
        "overlap": ((spec.get("overlap") or "none")
                    if spec["kind"] == "gpt" else None),
        "final_loss": round(final_loss, 4),
        "step_ms_p50": tstats["step_time_ms_p50"],
        "step_ms_p95": tstats["step_time_ms_p95"],
        "step_ms_max": tstats["step_time_ms_max"],
        "compile_s": round(t_compile, 3),
        # schedule-model bubble of what actually RAN (pipe.n_stages and the
        # degraded sched, not the requested flags); non-interleaved 1F1B
        # shares GPipe's (S-1)/(M+S-1) — its win is activation memory
        "bubble_fraction": round(schedule_bubble_fraction(
            pipe.n_stages, pipe.n_microbatches, sched), 4),
        "ici_bytes_per_step": (ici_info["ici_bytes_per_step"]
                               if ici_info else None),
    }


def measure_decode(windows: int = 5, cfg=None, prompt_len: int = 32,
                   b: int = 8, extra_batches: tuple = (1, 32)) -> dict:
    """Decode throughput: KV-cache vs full-prefix-recompute decoders.

    Default shape: the MXU-sized GPT (d=512, L=4, V=8192) generating 224
    tokens from a 32-token prompt, batch 8; ``cfg``/``prompt_len``/``b``
    exist so CPU smoke-drives can run the identical harness on a tiny
    model (n_new is always ``cfg.seq_len - prompt_len``). The recompute decoder re-forwards the whole
    T=256 buffer every step (O(T²) per sequence, models/gpt.py:make_decoder);
    the cached decoder pushes one token against per-layer K/V buffers
    (make_cached_decoder).

    Measurement discipline (learned the hard way, see BASELINE.md §decode):
    every dispatch gets a DISTINCT prompt from a resident pool and is closed
    by a forced host read of the output tokens. Re-dispatching a jitted fn
    with byte-identical inputs through the axon tunnel returned in ~80us —
    four orders of magnitude under the FLOP floor of the recompute decoder —
    i.e. the repeat call never re-executed (result served from a cache
    keyed on (executable, inputs), or an async handle block_until_ready
    does not actually force). Distinct inputs + a host read rule out both.
    Paired two-point windows (1 vs 3 back-to-back dispatches) then cancel
    the per-dispatch fixed cost exactly as in :func:`measure`.
    """
    import jax

    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_cached_decoder,
        make_decoder,
        make_gpt_stages,
    )

    default_shape = cfg is None and prompt_len == 32 and b == 8
    cfg = cfg or GPTConfig(vocab=8192, seq_len=256, d_model=512, n_heads=8,
                           n_layers=4)
    t0 = prompt_len
    n_new = cfg.seq_len - t0
    stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, n_stages=1)
    params = [s.params for s in stages]
    n_disp = 1 + windows * 4            # warm + (1+3) dispatches per window

    def prompt_pool(bb):
        return jax.block_until_ready(jax.random.randint(
            jax.random.key(1), (n_disp, bb, t0), 0, cfg.vocab))

    prompts = prompt_pool(b)
    key = jax.random.key(2)

    def timed(fn, prompts=prompts):
        it = iter(range(n_disp))

        def one():
            out = fn(params, prompts[next(it)], key)
            int(jax.device_get(out[0, -1]))          # forced host read

        one()                                        # compile + warm
        diffs = []
        for _ in range(windows):
            t_start = time.perf_counter()
            one()
            d1 = time.perf_counter() - t_start
            t_start = time.perf_counter()
            one()
            one()
            one()
            d3 = time.perf_counter() - t_start
            diffs.append((d3 - d1) / 2)
        diffs.sort()
        dt = diffs[len(diffs) // 2]
        if dt <= 0:
            raise RuntimeError(
                "decode two-point timing collapsed (median diff "
                f"{dt:.6f}s) - dispatch noise exceeds one decode window")
        return dt

    cached_s = timed(make_cached_decoder(stages, cfg, t0, n_new))
    recompute_s = timed(make_decoder(stages, t0, n_new))
    row = {
        "config": "gpt_decode",
        "prompt_len": t0, "n_new": n_new, "batch": b,
        "tokens_per_sec_cached": round(b * n_new / cached_s, 1),
        "tokens_per_sec_recompute": round(b * n_new / recompute_s, 1),
        "speedup": round(recompute_s / cached_s, 2),
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
    }
    # batched-decode columns (additive): the cached decoder at other batch
    # sizes — the per-batch-size baseline the serving sweep (--serve) is
    # judged against (a continuous batch of K slots should approach the
    # B=K one-shot column, and beat the B=1 sequential one)
    for bb in extra_batches:
        if bb == b:
            continue
        bs = timed(make_cached_decoder(stages, cfg, t0, n_new),
                   prompts=prompt_pool(bb))
        row[f"tokens_per_sec_cached_b{bb}"] = round(bb * n_new / bs, 1)
    if default_shape:
        # only the benchmark shape owns the artifact — CPU smoke-drives on
        # tiny cfgs must not clobber it
        with open(os.path.join(REPO, "benchmarks", "decode_timing.json"),
                  "w") as f:
            json.dump(row, f, indent=2)
    return row


def measure_serving(rates: tuple = (2.0, 8.0, 32.0), n_requests: int = 24,
                    slots: int = 8, max_new: int = 24, cfg=None,
                    prompt_lens: tuple = (8, 16, 32), block_size: int = 16,
                    compare: bool = True, lint: bool = False,
                    attn_kernel: str = "dense") -> list[dict]:
    """Offered-load sweep of the continuous-batching engine (serve/).

    One row per Poisson arrival rate through an ``slots``-slot engine, plus
    the ``gpt_serve_sequential`` baseline: the SAME workload at the top
    rate through a 1-slot engine — literal one-request-at-a-time decoding,
    which continuous batching must beat on aggregate tokens/sec (that gap
    is the whole subsystem's reason to exist; asserted in
    tests/test_serve.py on the CPU smoke shape). Each row reports
    throughput, TTFT/TPOT p50/p95 and mean slot occupancy — TTFT includes
    genuine queue wait once the offered load exceeds slot capacity.

    With ``compare=True`` two paged-vs-dense comparisons ride along
    (:func:`_measure_paged_vs_dense`): max sustainable concurrency at
    fixed KV-cache bytes, and p95 decode-tick latency under a long-prompt
    arrival (chunked vs monolithic prefill) — the two wins the paged pool
    exists for.

    Engines are warmed (every prefill bucket + the decode tick compiled)
    before the trace runs, so latency columns measure serving, not XLA
    compilation. ``cfg``/shape params exist so CPU smoke-drives can run the
    identical harness on a tiny model; only the default (MXU-sized) shape
    writes the ``benchmarks/serving.json`` artifact.
    """
    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.models.gpt import (
        GPTConfig,
        make_gpt_stages,
    )
    from simple_distributed_machine_learning_tpu.serve import (
        InferenceEngine,
        ServeMetrics,
        SimConfig,
        simulate,
    )

    default_shape = (cfg is None and slots == 8 and n_requests == 24
                     and max_new == 24 and rates == (2.0, 8.0, 32.0)
                     and prompt_lens == (8, 16, 32) and block_size == 16
                     and attn_kernel == "dense")
    cfg = cfg or GPTConfig(vocab=8192, seq_len=256, d_model=512, n_heads=8,
                           n_layers=4)
    if max(prompt_lens) + max_new > cfg.seq_len:
        raise ValueError(
            f"prompt {max(prompt_lens)} + max_new {max_new} exceeds "
            f"seq_len {cfg.seq_len}")
    stages, _, _ = make_gpt_stages(jax.random.key(0), cfg, n_stages=1)
    if lint:
        # --serve --lint: preflight the EXACT serving programs this sweep
        # is about to time — the paged sweep engines (including the 1-slot
        # sequential baseline) AND, with compare=True, the paged-vs-dense
        # comparison engines, whose n_slots/n_blocks/prefill_chunk are
        # traced shapes and contract bounds, i.e. DIFFERENT compiled
        # programs — abort before any compile/timing work on ERROR findings
        from simple_distributed_machine_learning_tpu.analysis.programs import (
            ServeSpec,
            lint_serve,
        )
        sspecs = [
            # the sweep rows and the 1-slot sequential baseline (n_slots is
            # a traced shape: different compiled programs)
            ServeSpec(cfg, n_slots=slots, kv_layout="paged",
                      block_size=block_size, prompt_lens=prompt_lens,
                      attn_kernel=attn_kernel),
            ServeSpec(cfg, n_slots=1, kv_layout="paged",
                      block_size=block_size, prompt_lens=prompt_lens,
                      attn_kernel=attn_kernel),
            # the kernel-comparison engines (both attention paths) and the
            # int8 pool the quantized fixed-mem rows build — each a
            # distinct compiled program family
            ServeSpec(cfg, n_slots=slots, kv_layout="paged",
                      block_size=block_size, prompt_lens=prompt_lens,
                      attn_kernel="fused"),
            ServeSpec(cfg, n_slots=slots, kv_layout="paged",
                      block_size=block_size, prompt_lens=prompt_lens,
                      cache_dtype="int8"),
            # the speculative comparison engines (draft == target): the
            # propose scan, the batched verify and the fused tick are
            # DIFFERENT compiled programs from the plain sweep's
            ServeSpec(cfg, n_slots=min(slots, 4), kv_layout="paged",
                      block_size=block_size, prompt_lens=prompt_lens,
                      spec_k=SPEC_BENCH_K, draft_cfg=cfg)]
        if compare:
            geo = _compare_geometries(cfg, slots=slots, max_new=max_new,
                                      prompt_lens=prompt_lens,
                                      block_size=block_size)
            for _label, kw in geo["fixed_mem"]:
                sspecs.append(ServeSpec(cfg, prompt_lens=prompt_lens, **kw))
            lp_lens = (min(prompt_lens), geo["long_len"])
            for _label, kw in geo["longprompt"]:
                sspecs.append(ServeSpec(cfg, prompt_lens=lp_lens, **kw))
            # the availability row's supervised engine (chunked prefill =
            # block_size bounds its recovery-retrace shapes) — a distinct
            # compiled geometry, so it preflights too
            sspecs.append(ServeSpec(cfg, n_slots=min(slots, 4),
                                    kv_layout="paged",
                                    block_size=block_size,
                                    prefill_chunk=block_size,
                                    prompt_lens=prompt_lens))
        seen = []
        for sspec in sspecs:
            if sspec in seen:
                continue
            seen.append(sspec)
            rep = lint_serve(stages, sspec,
                             draft_stages=(stages if sspec.spec_k else None))
            print(rep.format(costs=False))
            if not rep.ok():
                raise SystemExit("bench --serve: serve-program preflight "
                                 "found ERROR findings")
        print(f"bench --serve: lint preflight clean "
              f"({len(seen)} deployments"
              + (", paged + dense" if compare else ", paged") + ")")

    def run(rate, n_slots, label):
        engine = InferenceEngine(stages, cfg, n_slots=n_slots,
                                 block_size=block_size,
                                 attn_kernel=attn_kernel)
        # warm every compiled shape OUTSIDE the measured trace: one tiny
        # request per prompt-length bucket (prefill shapes) + decode ticks
        for t0 in prompt_lens:
            engine.submit(np.zeros(t0, np.int32), max_new_tokens=2)
        engine.drain()
        engine.metrics = metrics = ServeMetrics()
        rep = simulate(engine, SimConfig(
            n_requests=n_requests, rate=rate, seed=0,
            prompt_lens=prompt_lens, max_new_tokens=max_new))
        s = metrics.summary()
        return {
            "config": label, "rate": rate, "n_slots": n_slots,
            "n_requests": n_requests, "max_new_tokens": max_new,
            "completed": rep["completed"], "wall_s": rep["wall_s"],
            "tokens_per_sec": s["tokens_per_sec"],
            "ttft_ms_p50": s["ttft_ms_p50"], "ttft_ms_p95": s["ttft_ms_p95"],
            "tpot_ms_p50": s["tpot_ms_p50"], "tpot_ms_p95": s["tpot_ms_p95"],
            "slot_occupancy_mean": s["slot_occupancy_mean"],
            "tp": s.get("tp", 1), "spec_k": s.get("spec_k", 0),
            "accept_rate": s.get("spec_accept_rate"),
            "device_kind": jax.devices()[0].device_kind,
            "backend": jax.default_backend(),
        }

    rows = [run(max(rates), 1, "gpt_serve_sequential")]
    rows += [run(r, slots, "gpt_serve") for r in rates]
    if compare:
        rows += _measure_paged_vs_dense(stages, cfg, slots=slots,
                                        n_requests=n_requests,
                                        max_new=max_new,
                                        prompt_lens=prompt_lens,
                                        block_size=block_size)
        rows += _measure_spec_vs_plain(stages, cfg, slots=min(slots, 4),
                                       n_requests=n_requests,
                                       max_new=max_new,
                                       prompt_lens=prompt_lens,
                                       block_size=block_size)
        # the ISSUE-15 rows: fused-kernel vs dense per-tick HBM bytes +
        # ticks/sec, and the int8 pool's fixed-KV-bytes concurrency win
        rows += _measure_kernel_and_quant(stages, cfg, slots=min(slots, 4),
                                          n_requests=n_requests,
                                          max_new=max_new,
                                          prompt_lens=prompt_lens,
                                          block_size=block_size)
        # the availability row: completed-within-deadline fraction while a
        # mid-flight engine crash restarts through the serve supervisor
        rows += _measure_availability(stages, cfg, slots=min(slots, 4),
                                      n_requests=n_requests,
                                      max_new=max_new,
                                      prompt_lens=prompt_lens,
                                      block_size=block_size)
        # the fleet availability row: same question one level up — a whole
        # replica killed mid-decode, its in-flight requests migrated onto
        # the survivors from its journal alone (serve/fleet.py). The
        # per-replica engine geometry matches the availability row's, so
        # the --lint preflight and the build cache already cover it
        rows += _measure_fleet_availability(stages, cfg,
                                            slots=min(slots, 4),
                                            n_requests=n_requests,
                                            max_new=max_new,
                                            prompt_lens=prompt_lens,
                                            block_size=block_size)
        # the ISSUE-17 rows: disaggregated prefill/decode pools vs the
        # symmetric fleet (same burst, same replica count), and the host
        # offload tier's prefix-cache win under HBM pressure
        rows += _measure_disaggregation(stages, cfg,
                                        n_requests=n_requests,
                                        max_new=max_new,
                                        prompt_lens=prompt_lens,
                                        block_size=block_size)
        rows += _measure_host_offload(stages, cfg,
                                      n_requests=min(n_requests, 12),
                                      block_size=block_size)
        # the ISSUE-20 row: N LoRA tenants batched through one engine's
        # adapter bank vs N sequential dedicated merged-dense engines
        rows += _measure_multi_adapter(stages, cfg, slots=min(slots, 4),
                                       n_requests=min(n_requests, 12),
                                       max_new=max_new,
                                       prompt_lens=prompt_lens,
                                       block_size=block_size)
        # the ISSUE-19 row: what the always-on observability pipeline
        # (SLO engine + trace + TTFT attribution) costs per tick
        rows += _measure_slo_overhead(stages, cfg, slots=min(slots, 4),
                                      n_requests=n_requests,
                                      max_new=max_new,
                                      prompt_lens=prompt_lens,
                                      block_size=block_size)
    if default_shape:
        with open(os.path.join(REPO, "benchmarks", "serving.json"),
                  "w") as f:
            json.dump({"device": rows[0]["device_kind"],
                       "backend": rows[0]["backend"], "rows": rows},
                      f, indent=2)
    return rows


def _compare_geometries(cfg, slots: int, max_new: int, prompt_lens: tuple,
                        block_size: int) -> dict:
    """Engine-constructor kwargs for the paged-vs-dense comparison rows.

    Shared by ``--serve --lint`` (which must preflight the exact programs
    the comparison compiles — these geometries differ from the sweep
    engines in n_slots/n_blocks/prefill_chunk, all traced shapes) and
    :func:`_measure_paged_vs_dense` (which builds engines from them)."""
    mem_slots = max(2, slots // 4)          # the dense pool being matched
    bps = -(-cfg.seq_len // block_size)     # blocks per max_len sequence
    n_blocks = mem_slots * bps              # same bytes as the dense rows
    rows_per_req = max(prompt_lens) + max_new - 1
    blocks_per_req = -(-rows_per_req // block_size)
    paged_slots = min(32, max(mem_slots + 1, n_blocks // blocks_per_req))
    n_short = max(2, slots // 2)
    return {
        "fixed_mem": (
            ("gpt_serve_dense_fixed_mem",
             dict(n_slots=mem_slots, kv_layout="dense")),
            ("gpt_serve_paged_fixed_mem",
             dict(n_slots=paged_slots, kv_layout="paged",
                  block_size=block_size, n_blocks=n_blocks))),
        "longprompt": (
            ("gpt_serve_dense_longprompt",
             dict(n_slots=n_short + 1, kv_layout="dense")),
            ("gpt_serve_paged_chunked_longprompt",
             dict(n_slots=n_short + 1, kv_layout="paged",
                  block_size=block_size, prefill_chunk=block_size))),
        "long_len": cfg.seq_len - max_new,
        "n_short": n_short,
    }


def _drain_burst(engine, specs):
    """Submit everything at t=0 and drive to empty — the one burst-drain
    helper every comparison row family measures with. Returns
    ``(handles, ticks, tokens, peak concurrent active, completed,
    wall_s)``."""
    import time as _time

    handles = [engine.submit(**sp) for sp in specs]
    ticks, toks, peak = 0, 0, 0
    t0 = _time.perf_counter()
    while engine.busy:
        toks += engine.step()
        ticks += 1
        peak = max(peak, engine.pool.n_active)
    wall = _time.perf_counter() - t0
    done = sum(1 for h in handles if h.state == "done")
    return handles, ticks, toks, peak, done, wall


def _measure_paged_vs_dense(stages, cfg, slots: int, n_requests: int,
                            max_new: int, prompt_lens: tuple,
                            block_size: int,
                            parts: tuple = ("fixed_mem", "longprompt"),
                            ) -> list[dict]:
    """The two paged-pool claims, measured head to head (ROADMAP item #1):

    1. *Fixed KV memory, max sustainable concurrency* — a dense pool of
       ``mem_slots`` rows vs a paged pool of the SAME bytes
       (``mem_slots * blocks_per_seq`` blocks) given slots to spare. A
       burst workload arrives all at once; the peak number of
       simultaneously active requests is recorded. Dense caps at
       ``mem_slots`` (a row is reserved at ``max_len`` whether used or
       not); paged admits until actual blocks run out, so with requests
       shorter than ``max_len`` it sustains strictly more.

    2. *Prefill stall, p95 tick latency* — short requests decode steadily
       while one LONG prompt arrives mid-flight. Dense/monolithic runs the
       whole prompt inside one tick (every co-resident stalls for it);
       paged/chunked spreads it over ``block_size``-token chunks, so the
       worst decode tick shrinks. Per-tick wall latency is measured around
       ``engine.step()`` after the long submit.
    """
    import time as _time

    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.serve import (
        InferenceEngine,
    )

    rng = np.random.default_rng(7)
    dev = {"device_kind": jax.devices()[0].device_kind,
           "backend": jax.default_backend()}

    def _burst(engine, specs):
        """(peak concurrent active, completed, tokens/sec) of a burst."""
        _h, _ticks, toks, peak, done, wall = _drain_burst(engine, specs)
        return peak, done, round(toks / wall, 1)

    def _spec(t0, i):
        return dict(prompt=rng.integers(0, cfg.vocab, t0).astype(np.int32),
                    max_new_tokens=max_new, seed=1000 + i)

    # -- 1. fixed-memory concurrency --------------------------------------
    out = []
    geo = _compare_geometries(cfg, slots=slots, max_new=max_new,
                              prompt_lens=prompt_lens, block_size=block_size)
    paged_slots = geo["fixed_mem"][1][1]["n_slots"]
    burst = [_spec(prompt_lens[i % len(prompt_lens)], i)
             for i in range(max(n_requests, 2 * paged_slots))]
    for label, kw in geo["fixed_mem"]:
        if "fixed_mem" not in parts:
            break
        engine = InferenceEngine(stages, cfg, **kw)
        warm = [_spec(t0, 500) for t0 in prompt_lens]
        for sp in warm:
            engine.submit(**{**sp, "max_new_tokens": 2})
        engine.drain()
        peak, done, tps = _burst(engine, burst)
        out.append({
            "config": label, "n_slots": kw["n_slots"],
            "kv_bytes": int(engine.pool.kc.nbytes + engine.pool.vc.nbytes),
            "n_requests": len(burst), "completed": done,
            "max_concurrent": peak, "tokens_per_sec": tps, **dev,
        })

    # -- 2. long-prompt prefill stall -------------------------------------
    # the stress case: a prompt near the sequence budget, so the monolithic
    # prefill tick dwarfs a decode tick
    long_len = geo["long_len"]
    n_short = geo["n_short"]
    for label, kw in geo["longprompt"]:
        if "longprompt" not in parts:
            break
        engine = InferenceEngine(stages, cfg, **kw)
        # warm the exact compiled shapes: short prefill, long prefill
        # (its chunk lengths), the decode tick
        engine.submit(**{**_spec(min(prompt_lens), 600),
                         "max_new_tokens": 2})
        engine.submit(**{**_spec(long_len, 601), "max_new_tokens": 2})
        engine.drain()
        for i in range(n_short):
            engine.submit(**_spec(min(prompt_lens), 700 + i))
        for _ in range(3):                    # steady decode underway
            engine.step()
        engine.submit(**{**_spec(long_len, 800), "max_new_tokens": max_new})
        tick_ms = []
        while engine.busy:
            t0 = _time.perf_counter()
            engine.step()
            tick_ms.append((_time.perf_counter() - t0) * 1e3)
        out.append({
            "config": label, "n_slots": kw["n_slots"],
            "long_prompt_len": long_len, "n_short": n_short,
            "tick_ms_p50": round(float(np.percentile(tick_ms, 50)), 3),
            "tick_ms_p95": round(float(np.percentile(tick_ms, 95)), 3),
            "tick_ms_max": round(max(tick_ms), 3),
            "n_ticks": len(tick_ms), **dev,
        })
    return out


def _measure_kernel_and_quant(stages, cfg, slots: int, n_requests: int,
                              max_new: int, prompt_lens: tuple,
                              block_size: int) -> list[dict]:
    """The ISSUE-15 serve-path rows: the fused Pallas paged-attention
    kernel vs the gather-then-dense path, and the int8-quantized pool vs
    bf16 at fixed KV bytes.

    1. ``paged_attention_kernel`` (one row per kernel path) — the SAME
       burst workload drained through ``attn_kernel="dense"`` and
       ``"fused"`` engines: measured ticks/sec and tokens/sec ride along,
       and each row carries the ANALYZER's per-tick decode K/V bytes
       (``hbm_tick_costs`` over ``engine_spec`` — the exact deployment,
       not a parallel description). The dense row's bytes include the
       ``decode.kv_attn_reread`` pass the kernel eliminates, so
       ``hbm_reduction`` on the fused row is the single-pass win (2x);
       greedy token streams are asserted IDENTICAL across the two engines
       (the bit-exactness anchor, run on every bench round).

    2. ``gpt_serve_quantized_fixed_mem`` (one row per cache dtype) — a
       bf16 pool and an int8 pool sized from the SAME byte budget
       (``n_blocks_for_bytes``, scale planes billed), drained under an
       all-at-once burst; ``max_concurrent`` is the resident-request
       count the quantized pool exists to multiply. The int8 row carries
       ``resident_ratio`` vs bf16 (the >= 2x gate the CI smoke and
       tests/test_paged_attention.py assert).
    """
    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.analysis.programs import (
        engine_spec,
        hbm_tick_costs,
    )
    from simple_distributed_machine_learning_tpu.serve import (
        InferenceEngine,
    )
    from simple_distributed_machine_learning_tpu.serve.slots import (
        kv_block_bytes,
        n_blocks_for_bytes,
    )

    dev = {"device_kind": jax.devices()[0].device_kind,
           "backend": jax.default_backend()}
    rng = np.random.default_rng(11)

    def _specs(n, seed0=0):
        return [dict(prompt=rng.integers(
                         0, cfg.vocab,
                         prompt_lens[i % len(prompt_lens)]).astype(np.int32),
                     max_new_tokens=max_new, seed=seed0 + i)
                for i in range(n)]

    out = []
    # -- 1. dense vs fused kernel path -------------------------------------
    streams = {}
    burst = _specs(n_requests)
    for kernel in ("dense", "fused"):
        engine = InferenceEngine(stages, cfg, n_slots=slots,
                                 block_size=block_size, attn_kernel=kernel)
        for t0 in prompt_lens:       # warm every compiled shape
            engine.submit(np.zeros(t0, np.int32), max_new_tokens=2)
        engine.drain()
        handles, ticks, toks, _peak, done, wall = _drain_burst(
            engine, [dict(sp) for sp in burst])
        streams[kernel] = [list(h.tokens) for h in handles]
        costs = {h.op: h.bytes_per_tick
                 for h in hbm_tick_costs(engine_spec(engine),
                                         n_layers=engine._n_layers)}
        decode_bytes = (costs["decode.kv_gather"]
                        + costs.get("decode.kv_attn_reread", 0))
        out.append({
            "config": "paged_attention_kernel", "kernel": kernel,
            "n_slots": slots, "n_requests": n_requests,
            "completed": done, "ticks": ticks,
            "ticks_per_sec": round(ticks / wall, 1),
            "tokens_per_sec": round(toks / wall, 1),
            "decode_kv_bytes_per_tick": decode_bytes, **dev,
        })
    # the bit-exactness anchor, REPORTED rather than raised: on a real
    # accelerator the kernel's different accumulation order may flip a
    # genuine near-tie argmax (the tests/tolerances.py budget), and a
    # measurement round must record that, not abort. Sparse flips within
    # the near-tie budget report bit_exact false with the fraction; a
    # wholesale divergence (a real math bug) still fails loudly
    flat_d = [t for s_ in streams["dense"] for t in s_]
    flat_f = [t for s_ in streams["fused"] for t in s_]
    mismatch = (sum(a != b for a, b in zip(flat_d, flat_f))
                / max(len(flat_d), 1))
    if mismatch > 0.25:    # pragma: no cover - gate
        raise AssertionError(
            f"bench: fused-kernel greedy streams diverged {mismatch:.0%} "
            f"from the dense path — beyond any near-tie budget, the "
            f"parity anchor is broken")
    dense_b = out[-2]["decode_kv_bytes_per_tick"]
    fused_b = out[-1]["decode_kv_bytes_per_tick"]
    out[-1]["hbm_reduction"] = round(dense_b / fused_b, 2)
    out[-1]["streams_bit_exact"] = mismatch == 0
    if mismatch:           # pragma: no cover - near-tie corner on-chip
        out[-1]["stream_mismatch_fraction"] = round(mismatch, 4)
        sys.stderr.write(
            f"bench: fused streams flipped {mismatch:.2%} of tokens "
            f"(near-tie argmax under reordered accumulation)\n")

    # -- 2. int8 vs bf16 resident requests at fixed KV bytes ---------------
    L = sum(len(p["blocks"]) for p in (s.params for s in stages))
    dh = cfg.d_model // cfg.n_heads
    # cap the pools' per-sequence budget at the workload's footprint (the
    # pool refuses a capacity that cannot hold one full sequence, and the
    # comparison is about RESIDENT REQUESTS, not unreachable headroom)
    ml_q = max(prompt_lens) + max_new
    bpr = -(-ml_q // block_size)         # == the pools' blocks_per_seq
    # a realistic non-divisible budget: 2 requests' worth of bf16 blocks
    # plus one stranded block (fixed budgets never divide evenly)
    budget = (2 * bpr + 1) * kv_block_bytes(L, cfg.n_heads, block_size, dh,
                                            "bfloat16")
    base_concurrent = None
    for cd in ("bfloat16", "int8"):
        nb = n_blocks_for_bytes(budget, L, cfg.n_heads, block_size, dh, cd)
        n_slots_q = min(32, max(2, nb // bpr + 1))
        engine = InferenceEngine(stages, cfg, n_slots=n_slots_q,
                                 max_len=ml_q, block_size=block_size,
                                 n_blocks=nb, cache_dtype=cd)
        for t0 in prompt_lens:
            engine.submit(np.zeros(t0, np.int32), max_new_tokens=2)
        engine.drain()
        # every request the longest shape: the budget maths above sized
        # the pool for exactly this per-request footprint
        specs = [dict(prompt=rng.integers(0, cfg.vocab,
                                          max(prompt_lens)).astype(np.int32),
                      max_new_tokens=max_new, seed=700 + i)
                 for i in range(max(n_requests, 3 * n_slots_q))]
        _h, _ticks, toks, peak, done, wall = _drain_burst(engine, specs)
        row = {
            "config": "gpt_serve_quantized_fixed_mem", "cache_dtype": cd,
            "kv_budget_bytes": int(budget), "n_blocks": nb,
            "n_slots": n_slots_q, "bytes_per_block": engine.pool.
            bytes_per_block, "n_requests": len(specs), "completed": done,
            "max_concurrent": peak,
            "tokens_per_sec": round(toks / wall, 1), **dev,
        }
        if base_concurrent is None:
            base_concurrent = peak
        else:
            row["resident_ratio"] = round(peak / base_concurrent, 2)
        out.append(row)
    return out


# verify width of the speculative bench comparison (and its lint spec):
# draft == target makes every greedy proposal accepted, so the tick emits
# exactly SPEC_BENCH_K tokens per slot — the amortization ceiling
SPEC_BENCH_K = 4


def _measure_spec_vs_plain(stages, cfg, slots: int, n_requests: int,
                           max_new: int, prompt_lens: tuple,
                           block_size: int, spec_k: int = SPEC_BENCH_K
                           ) -> list:
    """Speculative-vs-plain aggregate throughput on the SAME workload with
    ``draft == target`` — every greedy proposal verifies, so acceptance
    pins at 1.0 and each speculative tick emits ``spec_k`` tokens per
    decoding slot (the amortization ceiling, isolated from draft quality).

    The GATED numbers are tokens per engine TICK, measured by draining the
    identical all-submitted-up-front workload through both engines and
    counting ``engine.step()`` calls: a tick is one fixed program-dispatch
    round (the launch + weight/KV-stream cost speculative decoding exists
    to amortize), and the tick counts are fully deterministic — the same
    on every machine — so tests/CI can assert the >= 2x amortization bar
    without flaking on a loaded box. Real wall tokens/sec for both modes
    ride along as informational columns (on real accelerators the wall
    speedup is what the per-tick cost argument predicts; on a tiny CPU
    smoke shape wall time is host-noise-dominated, which is exactly why
    the gate counts ticks)."""
    import time as _time

    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.serve import (
        InferenceEngine,
        ServeMetrics,
    )

    def run(spec: bool) -> dict:
        kw = dict(kv_layout="paged", block_size=block_size)
        if spec:
            kw.update(draft_stages=stages, draft_cfg=cfg, spec_k=spec_k)
        engine = InferenceEngine(stages, cfg, n_slots=slots, **kw)
        for t0 in prompt_lens:    # warm every compiled shape
            engine.submit(np.zeros(t0, np.int32), max_new_tokens=2)
        engine.drain()
        engine.metrics = metrics = ServeMetrics()
        rng = np.random.default_rng(0)
        t0w = _time.perf_counter()
        for i in range(n_requests):
            engine.submit(
                rng.integers(0, cfg.vocab,
                             prompt_lens[i % len(prompt_lens)]).astype(
                                 np.int32),
                max_new_tokens=max_new)
        ticks = 0
        while engine.busy:
            engine.step()
            ticks += 1
        wall = _time.perf_counter() - t0w
        s = metrics.summary()
        tokens = n_requests * max_new
        return {"ticks": ticks, "tokens_per_tick": round(tokens / ticks, 3),
                "wall_tokens_per_sec": round(tokens / wall, 1),
                "accept_rate": s.get("spec_accept_rate")}

    sr, pr = run(True), run(False)
    return [{
        "config": "gpt_serve_spec_vs_plain", "n_slots": slots,
        "n_requests": n_requests, "max_new_tokens": max_new,
        "spec_k": spec_k, "accept_rate": sr["accept_rate"],
        # the deterministic gate columns: same workload, counted ticks
        "ticks_spec": sr["ticks"], "ticks_plain": pr["ticks"],
        "tokens_per_tick_spec": sr["tokens_per_tick"],
        "tokens_per_tick_plain": pr["tokens_per_tick"],
        "speedup_vs_plain": round(sr["tokens_per_tick"]
                                  / pr["tokens_per_tick"], 2),
        # informational wall-clock columns
        "wall_tokens_per_sec_spec": sr["wall_tokens_per_sec"],
        "wall_tokens_per_sec_plain": pr["wall_tokens_per_sec"],
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
    }]


def _measure_availability(stages, cfg, slots: int, n_requests: int,
                          max_new: int, prompt_lens: tuple,
                          block_size: int, deadline_s: float = 120.0,
                          crash_tick: int = 5, max_restarts: int = 3
                          ) -> list:
    """Serving availability under an injected engine crash: the fraction
    of requests that complete WITHIN their deadline while the serve
    supervisor (``serve/supervisor.py``) rebuilds the crashed engine and
    recovers every in-flight request from the journal.

    One ``engine-crash@serve.tick`` fires mid-flight; the row reports
    ``availability`` = completed-within-deadline / submitted (requests the
    supervisor shed on an expired deadline count AGAINST availability —
    that is the metric's point), the restart count, and how many requests
    were recovered from the journal.  With the default generous deadline
    the smoke shape pins availability == 1.0 and restarts >= 1
    (tests/test_serve_supervisor.py): a crash costs a restart, not
    completions.  Tightening ``deadline_s`` turns the same harness into a
    recovery-latency budget measurement."""
    import tempfile
    import time as _time

    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.resilience import faults
    from simple_distributed_machine_learning_tpu.serve import (
        ServeMetrics,
        ServeSupervisor,
        engine_factory,
    )

    metrics = ServeMetrics()
    plan = faults.install(faults.FaultPlan.parse(
        f"engine-crash@serve.tick={crash_tick}"))
    tmpdir = tempfile.TemporaryDirectory(prefix="sdml-bench-journal-")
    try:
        sup = ServeSupervisor(
            # chunked prefill bounds the recovery re-prefill to chunk-sized
            # compiled shapes (the engine.preempt compile-cost note)
            engine_factory(stages, cfg, n_slots=slots, kv_layout="paged",
                           block_size=block_size, prefill_chunk=block_size,
                           metrics=metrics),
            os.path.join(tmpdir.name, "journal.jsonl"), metrics=metrics,
            max_restarts=max_restarts, default_deadline_s=deadline_s,
            # the crash forensics ride along: the injected restart must
            # leave a post-mortem bundle (flight rows + request states +
            # journal tail), and the row reports how many were written
            postmortem_dir=tmpdir.name)
        rng = np.random.default_rng(0)
        t0w = _time.perf_counter()
        for i in range(n_requests):
            sup.submit(
                rng.integers(0, cfg.vocab,
                             prompt_lens[i % len(prompt_lens)]).astype(
                                 np.int32),
                max_new_tokens=max_new)
        sup.drain()
        sup.close()
        wall = _time.perf_counter() - t0w
        postmortems = len(sup.postmortems)
    finally:
        faults.uninstall()
        tmpdir.cleanup()
    s = metrics.summary()
    completed = sum(1 for r in sup.requests.values() if r.state == "done")
    return [{
        "config": "gpt_serve_availability_crash", "n_slots": slots,
        "n_requests": n_requests, "max_new_tokens": max_new,
        "deadline_s": deadline_s, "crash_tick": crash_tick,
        # the headline: completed-within-deadline fraction under the crash
        "availability": round(completed / n_requests, 4),
        "completed": completed,
        "shed_deadline": s.get("shed_by_reason", {}).get("deadline", 0),
        "restarts": s.get("restarts", 0),
        "recovered_requests": s.get("recovered_requests", 0),
        "postmortem_bundles": postmortems,
        "faults_fired": plan.stats()["total_fired"],
        "wall_s": round(wall, 3),
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
    }]


def _measure_fleet_availability(stages, cfg, n_requests: int, max_new: int,
                                prompt_lens: tuple, block_size: int,
                                replicas: int = 3, slots: int = 4,
                                deadline_s: float = 120.0,
                                kill_tick: int = 5) -> list:
    """Serving availability under a WHOLE-REPLICA loss: a 3-replica fleet
    (``serve/fleet.py``) loses one replica mid-decode
    (``replica-kill@fleet.tick``) and must migrate its in-flight requests
    onto the survivors from the dead replica's journal alone.

    ``availability`` = completed-within-deadline / submitted, like
    :func:`_measure_availability` one level down — with the default
    generous deadline the smoke shape pins availability == 1.0 with
    ``replica_losses == 1`` and ``migrations >= 1``
    (tests/test_fleet.py): losing a replica costs a migration, never a
    completion."""
    import tempfile
    import time as _time

    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.resilience import faults
    from simple_distributed_machine_learning_tpu.serve import (
        ServeFleet,
        ServeMetrics,
        engine_factory,
    )

    metrics = ServeMetrics()
    plan = faults.install(faults.FaultPlan.parse(
        f"replica-kill@fleet.tick={kill_tick}"))
    tmpdir = tempfile.TemporaryDirectory(prefix="sdml-bench-fleet-")
    try:
        fleet = ServeFleet(
            engine_factory(stages, cfg, n_slots=slots, kv_layout="paged",
                           block_size=block_size, prefill_chunk=block_size,
                           metrics=metrics),
            tmpdir.name, n_replicas=replicas, metrics=metrics,
            default_deadline_s=deadline_s)
        rng = np.random.default_rng(0)
        t0w = _time.perf_counter()
        for i in range(n_requests):
            fleet.submit(
                rng.integers(0, cfg.vocab,
                             prompt_lens[i % len(prompt_lens)]).astype(
                                 np.int32),
                max_new_tokens=max_new)
        fleet.drain()
        fleet.close()
        wall = _time.perf_counter() - t0w
    finally:
        faults.uninstall()
        tmpdir.cleanup()
    s = metrics.summary()
    completed = sum(1 for r in fleet.requests.values()
                    if r.state == "done")
    return [{
        "config": "gpt_serve_fleet_availability_replica_loss",
        "replicas": replicas, "n_slots": slots,
        "n_requests": n_requests, "max_new_tokens": max_new,
        "deadline_s": deadline_s, "kill_tick": kill_tick,
        # the headline: completed-within-deadline fraction under the loss
        "availability": round(completed / n_requests, 4),
        "completed": completed,
        "shed_deadline": s.get("shed_by_reason", {}).get("deadline", 0),
        "replica_losses": fleet.replica_losses,
        "migrations": fleet.migrations,
        "affinity_hits": s.get("route_affinity_hits", 0),
        "faults_fired": plan.stats()["total_fired"],
        "wall_s": round(wall, 3),
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
    }]


def _measure_disaggregation(stages, cfg, n_requests: int, max_new: int,
                            prompt_lens: tuple, block_size: int,
                            replicas: int = 4, prefill_replicas: int = 2,
                            slots: int = 2) -> list:
    """Disaggregated prefill/decode pools vs the symmetric fleet
    (``serve/fleet.py``, ISSUE 17): the SAME burst of requests through the
    same replica count both ways. In the symmetric fleet every slot is
    shared between prefilling new arrivals and decoding old ones, so
    lingering decodes block fresh prefills; disaggregated, the prefill
    pool's slots free at end-of-prefill (the journal snap/adopt handoff
    moves the request to the decode pool) and TTFT tracks prefill-pool
    turnover only. The row reports TTFT p95 both ways plus the handoff
    count; the exact-pinned virtual-clock gate lives in
    ``resilience/scenarios.py::disagg-prefill-heavy``."""
    import tempfile
    import time as _time

    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.serve import (
        ServeFleet,
        ServeMetrics,
        engine_factory,
    )

    def run(n_prefill):
        metrics = ServeMetrics()
        tmpdir = tempfile.TemporaryDirectory(prefix="sdml-bench-disagg-")
        try:
            fleet = ServeFleet(
                engine_factory(stages, cfg, n_slots=slots,
                               kv_layout="paged", block_size=block_size,
                               prefill_chunk=block_size, metrics=metrics),
                tmpdir.name, n_replicas=replicas,
                prefill_replicas=n_prefill, metrics=metrics)
            rng = np.random.default_rng(0)
            t0 = _time.perf_counter()
            for i in range(n_requests):
                fleet.submit(
                    rng.integers(0, cfg.vocab,
                                 prompt_lens[i % len(prompt_lens)]).astype(
                                     np.int32),
                    max_new_tokens=max_new)
            fleet.drain()
            fleet.close()
            wall = _time.perf_counter() - t0
        finally:
            tmpdir.cleanup()
        completed = sum(1 for r in fleet.requests.values()
                        if r.state == "done")
        return metrics.summary(), wall, fleet.handoffs, completed

    sym, sym_wall, _, sym_done = run(0)
    dis, dis_wall, handoffs, dis_done = run(prefill_replicas)
    return [{
        "config": "gpt_serve_disagg_prefill_decode",
        "replicas": replicas, "prefill_replicas": prefill_replicas,
        "n_slots": slots, "n_requests": n_requests,
        "max_new_tokens": max_new,
        "completed": dis_done, "completed_symmetric": sym_done,
        "handoffs": handoffs,
        "ttft_ms_p95": dis.get("ttft_ms_p95"),
        "ttft_ms_p95_symmetric": sym.get("ttft_ms_p95"),
        "tokens_per_sec": dis.get("tokens_per_sec"),
        "tokens_per_sec_symmetric": sym.get("tokens_per_sec"),
        "wall_s": round(dis_wall, 3),
        "wall_s_symmetric": round(sym_wall, 3),
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
    }]


def _measure_host_offload(stages, cfg, n_requests: int,
                          block_size: int, slots: int = 2) -> list:
    """The host offload tier's prefix-cache win under HBM pressure
    (``serve/slots.py``, ISSUE 17): alternate hot-prefix requests with
    prefix-less scans through a pool sized to ONE full sequence, with and
    without the host tier. Each scan evicts the idle shared prefix; the
    HBM-only pool discards it (the next hot request re-prefills from
    scratch) while the tiered pool demotes it to host RAM and the router's
    affinity probe starts the prefetch upload back at submit time. The
    row pins the mechanism end to end: demotions, promotions, prefetch
    hits and the device prefix-hit gap over the HBM-only baseline."""
    import tempfile
    import time as _time

    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.serve import (
        ServeFleet,
        ServeMetrics,
        engine_factory,
    )

    bs = block_size
    prefix = np.arange(2 * bs, dtype=np.int32) % cfg.vocab
    max_len = 6 * bs                   # the scan's full extent
    n_blocks = 6                       # exactly one full sequence: maximal
    #                                    pressure, every scan evicts

    def run(host_blocks):
        metrics = ServeMetrics()
        tmpdir = tempfile.TemporaryDirectory(prefix="sdml-bench-host-")
        try:
            fleet = ServeFleet(
                engine_factory(stages, cfg, n_slots=slots,
                               kv_layout="paged", block_size=bs,
                               n_blocks=n_blocks, max_len=max_len,
                               prefill_chunk=bs,
                               host_cache_blocks=host_blocks,
                               metrics=metrics),
                tmpdir.name, n_replicas=1, metrics=metrics)
            rng = np.random.default_rng(0)
            t0 = _time.perf_counter()
            for i in range(n_requests):
                if i % 2 == 0:         # hot: shared prefix + unique tail
                    prompt = np.concatenate(
                        [prefix,
                         rng.integers(0, cfg.vocab, bs).astype(np.int32)])
                    fleet.submit(prompt, max_new_tokens=bs)
                else:                  # scan: prefix-less, pool-filling
                    fleet.submit(
                        rng.integers(0, cfg.vocab, 4 * bs).astype(np.int32),
                        max_new_tokens=2 * bs)
                fleet.drain()          # sequential: each scan's eviction
                #                        lands before the next hot arrival
            fleet.close()
            wall = _time.perf_counter() - t0
        finally:
            tmpdir.cleanup()
        return metrics.summary(), wall

    base, base_wall = run(0)
    tier, tier_wall = run(n_blocks)
    return [{
        "config": "gpt_serve_host_offload_prefix",
        "n_slots": slots, "n_requests": n_requests,
        "block_size": bs, "n_blocks": n_blocks,
        "host_cache_blocks": n_blocks,
        "prefix_hit_blocks": tier.get("prefix_hit_blocks", 0),
        "prefix_hit_blocks_hbm_only": base.get("prefix_hit_blocks", 0),
        "host_demotes": tier.get("host_demotes", 0),
        "host_promotes": tier.get("host_promotes", 0),
        "host_prefetch_hits": tier.get("host_prefetch_hits", 0),
        "host_transfer_bytes": tier.get("host_transfer_bytes", 0),
        "wall_s": round(tier_wall, 3),
        "wall_s_hbm_only": round(base_wall, 3),
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
    }]


def _measure_multi_adapter(stages, cfg, slots: int, n_requests: int,
                           max_new: int, prompt_lens: tuple,
                           block_size: int, n_adapters: int = 3,
                           rank: int = 4) -> list:
    """Multi-tenant LoRA serving's consolidation claim (ISSUE 20),
    measured head to head: N tenants through ONE engine — shared base
    weights plus a gathered adapter bank, every tick batching whatever
    tenant mix is resident — vs the dedicated deployment, N engines each
    serving its tenant's merged ``W + A @ B`` weights one after the
    other. Same prompts, same decode lengths, same total request count.
    The row reports tokens/sec both ways and the memory story: the
    bank's resident bytes vs the ``N - 1`` extra full parameter copies
    the dedicated deployment pays (the adapter path keeps ONE base
    copy)."""
    import dataclasses as _dc
    import time as _time

    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.models import lora
    from simple_distributed_machine_learning_tpu.serve import (
        InferenceEngine,
    )
    from simple_distributed_machine_learning_tpu.serve.adapters import (
        AdapterStore,
    )

    rng = np.random.default_rng(11)
    names = [f"tenant-{k}" for k in range(n_adapters)]
    adapters = {name: lora.init_lora_adapter(jax.random.key(100 + k),
                                             cfg, rank)
                for k, name in enumerate(names)}
    prompts = [rng.integers(0, cfg.vocab,
                            prompt_lens[i % len(prompt_lens)])
               .astype(np.int32) for i in range(n_requests)]
    tenant_of = [names[i % n_adapters] for i in range(n_requests)]
    params_list = [s.params for s in stages]
    base_bytes = int(sum(x.nbytes for x in jax.tree.leaves(params_list)))

    def _warm(engine, adapter=None):
        # compile every shape outside the timed window (both sides pay
        # their tracing up front, so the row measures steady-state ticks)
        for t0 in sorted(set(len(p) for p in prompts)):
            engine.submit(rng.integers(0, cfg.vocab, t0).astype(np.int32),
                          max_new_tokens=2, adapter=adapter)
        engine.drain()

    # -- one engine, N tenants batched through the adapter bank ----------
    store = AdapterStore(cfg, rank, slots)
    for name in names:
        store.register(name, adapters[name])
    multi = InferenceEngine(stages, cfg, n_slots=slots,
                            block_size=block_size, adapters=store)
    _warm(multi, adapter=names[0])
    handles = []
    t0 = _time.perf_counter()
    for i, prompt in enumerate(prompts):
        handles.append(multi.submit(prompt, max_new_tokens=max_new,
                                    seed=2000 + i,
                                    adapter=tenant_of[i]))
    toks = 0
    while multi.busy:
        toks += multi.step()
    multi_wall = _time.perf_counter() - t0
    multi_done = sum(1 for h in handles if h.state == "done")

    # -- the dedicated baseline: one merged-dense engine per tenant ------
    merged_wall, merged_done, merged_toks = 0.0, 0, 0
    for name in names:
        merged = [_dc.replace(s, params=p) for s, p in
                  zip(stages, lora.merge_adapter(params_list,
                                                 adapters[name]))]
        engine = InferenceEngine(merged, cfg, n_slots=slots,
                                 block_size=block_size)
        _warm(engine)
        mine = [i for i in range(n_requests) if tenant_of[i] == name]
        t0 = _time.perf_counter()
        hs = [engine.submit(prompts[i], max_new_tokens=max_new,
                            seed=2000 + i) for i in mine]
        while engine.busy:
            merged_toks += engine.step()
        merged_wall += _time.perf_counter() - t0
        merged_done += sum(1 for h in hs if h.state == "done")

    return [{
        "config": "gpt_serve_multi_adapter",
        "n_adapters": n_adapters, "adapter_rank": rank,
        "n_slots": slots, "n_requests": n_requests,
        "max_new_tokens": max_new,
        "completed": multi_done,
        "completed_merged_sequential": merged_done,
        "tokens_per_sec": round(toks / multi_wall, 1),
        "tokens_per_sec_merged_sequential": round(
            merged_toks / merged_wall, 1),
        "adapter_resident_bytes": store.resident_bytes,
        "adapter_swaps": store.swaps_total,
        "base_param_bytes": base_bytes,
        "merged_param_bytes_total": n_adapters * base_bytes,
        "param_bytes_saved": (n_adapters - 1) * base_bytes
        - store.resident_bytes,
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
    }]


def _measure_slo_overhead(stages, cfg, slots: int, n_requests: int,
                          max_new: int, prompt_lens: tuple,
                          block_size: int) -> list:
    """Cost of the ISSUE-19 observability pipeline: the identical
    supervised serve run with the SLO engine + request trace +
    TTFT attribution ON vs OFF, reported as ticks/sec both ways.

    The ON side binds an :class:`~telemetry.slo.SLOEngine` (windowed
    quantile histograms + per-tick burn-rate alert evaluation) and an
    in-memory :class:`~serve.tracing.ServeTrace`, then folds every
    request through :func:`~telemetry.attribution.attribute` after the
    drain — the full always-on production telemetry path.  The OFF side
    is the bare supervisor.  Both sides share engine geometry (and so
    the decode build cache and every compiled shape), and a warmup pass
    runs first so neither measured side pays compile time."""
    import tempfile
    import time as _time

    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.serve import (
        ServeMetrics,
        ServeSupervisor,
        engine_factory,
    )
    from simple_distributed_machine_learning_tpu.serve.tracing import (
        ServeTrace,
    )
    from simple_distributed_machine_learning_tpu.telemetry.attribution import (
        attribute,
    )
    from simple_distributed_machine_learning_tpu.telemetry.slo import (
        SLOEngine,
        SLOObjective,
    )

    def run(with_slo: bool, n: int):
        metrics = ServeMetrics()
        slo = (SLOEngine([SLOObjective("bench", ttft_slo_ms=50.0,
                                       tpot_slo_ms=20.0)],
                         registry=metrics.registry) if with_slo else None)
        trace = ServeTrace() if with_slo else None
        tmpdir = tempfile.TemporaryDirectory(prefix="sdml-bench-slo-")
        try:
            sup = ServeSupervisor(
                engine_factory(stages, cfg, n_slots=slots, kv_layout="paged",
                               block_size=block_size,
                               prefill_chunk=block_size, metrics=metrics),
                os.path.join(tmpdir.name, "journal.jsonl"),
                metrics=metrics, trace=trace, slo=slo)
            rng = np.random.default_rng(0)
            t0 = _time.perf_counter()
            for i in range(n):
                sup.submit(
                    rng.integers(0, cfg.vocab,
                                 prompt_lens[i % len(prompt_lens)]).astype(
                                     np.int32),
                    max_new_tokens=max_new, cls="bench")
            sup.drain()
            att = (attribute(trace.rows, registry=metrics.registry)
                   if with_slo else None)
            wall = _time.perf_counter() - t0
            ticks = sup.tick
            sup.close()
        finally:
            tmpdir.cleanup()
        return ticks, wall, att, slo

    run(False, min(n_requests, len(prompt_lens)))   # warmup: compile shapes
    off_ticks, off_wall, _, _ = run(False, n_requests)
    on_ticks, on_wall, att, slo = run(True, n_requests)
    return [{
        "config": "gpt_serve_slo_overhead",
        "n_slots": slots, "n_requests": n_requests,
        "max_new_tokens": max_new,
        "ticks": on_ticks, "ticks_off": off_ticks,
        "ticks_per_sec": round(on_ticks / on_wall, 1) if on_wall else None,
        "ticks_per_sec_off": (round(off_ticks / off_wall, 1)
                              if off_wall else None),
        "wall_s": round(on_wall, 3), "wall_s_off": round(off_wall, 3),
        "overhead_frac": (round(on_wall / off_wall - 1.0, 4)
                          if off_wall else None),
        "slo_evaluations": slo.evaluations,
        "alert_transitions": len(slo.alerts.journal),
        "attributed_requests": att["requests"],
        "attribution_max_drift_ms": att["max_abs_drift_ms"],
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
    }]


def _measure_sentinel(n_steps: int = 48, fault_step: int = 30,
                      snapshot_every: int = 4) -> list:
    """Self-healing training cost and recovery (``resilience/sentinel.py``).

    Two rows from the same small MLP workload:

    - ``train_sentinel_overhead``: steady steps/sec with the sentinel OFF
      vs ON (no faults) — the price of the per-step host sync + the
      every-K-steps snapshot gather.
    - ``train_sentinel_recovery``: an injected ``nan-grad`` at a fixed
      step; the row pins recovered == True (run completes, >= 1 rollback,
      the fault actually fired — the anti-vacuous gate) and reports the
      replayed-step budget (at most ``snapshot_every - 1`` by
      construction), quarantined batches and the ring's resident bytes.
    """
    import time as _time

    import jax
    import numpy as np

    from simple_distributed_machine_learning_tpu.data.mnist import Dataset
    from simple_distributed_machine_learning_tpu.models.mlp import (
        make_mlp_stages,
    )
    from simple_distributed_machine_learning_tpu.parallel.mesh import (
        make_mesh,
    )
    from simple_distributed_machine_learning_tpu.parallel.pipeline import (
        Pipeline,
    )
    from simple_distributed_machine_learning_tpu.resilience import faults
    from simple_distributed_machine_learning_tpu.train.trainer import (
        TrainConfig,
        Trainer,
    )

    rng = np.random.default_rng(0)
    batch, n_batches = 64, 12
    ds = Dataset(rng.standard_normal((batch * n_batches, 64),
                                     dtype=np.float32),
                 rng.integers(0, 10, batch * n_batches).astype(np.int32))
    epochs = max(1, n_steps // n_batches)

    def run(sentinel: bool, plan: str | None = None):
        stages, wd, od = make_mlp_stages(jax.random.key(0),
                                         [64, 128, 64, 10], 1)
        pipe = Pipeline(stages, make_mesh(n_stages=1, n_data=1,
                                          devices=jax.devices()[:1]),
                        wd, od)
        cfg = TrainConfig(epochs=epochs, batch_size=batch,
                          print_throughput=False, sentinel=sentinel,
                          sentinel_snapshot_every=snapshot_every)
        tr = Trainer(pipe, ds, ds, cfg)
        tr._print = lambda msg: None     # keep bench stdout row-clean
        installed = (faults.install(faults.FaultPlan.parse(plan))
                     if plan else None)
        t0 = _time.perf_counter()
        try:
            tr.fit()
        finally:
            # only uninstall what THIS run installed: a bare baseline run
            # must not clobber the SDML_CHAOS env plan main() installed
            # for the wedged-probe drill
            if installed is not None:
                faults.uninstall()
        wall = _time.perf_counter() - t0
        fired = installed.stats()["total_fired"] if installed else 0
        return tr, wall, fired

    _, wall_off, _ = run(sentinel=False)
    tr_on, wall_on, _ = run(sentinel=True)
    steps = epochs * n_batches
    tr_rec, _, fired = run(sentinel=True,
                           plan=f"nan-grad@train.grad={fault_step}")
    stats = tr_rec.sentinel_stats()
    return [{
        "config": "train_sentinel_overhead",
        "steps": steps,
        "steps_per_sec_off": round(steps / wall_off, 2),
        "steps_per_sec_on": round(steps / wall_on, 2),
        "overhead_frac": round(max(0.0, 1.0 - wall_off / wall_on), 4),
        "snapshot_every": snapshot_every,
        "ring_bytes": tr_on.sentinel_stats()["snapshot_ring_bytes"],
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
    }, {
        "config": "train_sentinel_recovery",
        "fault": f"nan-grad@train.grad={fault_step}",
        "faults_fired": fired,
        "anomalies": stats["anomalies"],
        "rollbacks": stats["rollbacks"],
        "quarantined_batches": stats["quarantined_batches"],
        # replay budget: rollback lands on the newest pre-anomaly snapshot
        "max_replayed_steps": snapshot_every - 1,
        "recovered": bool(fired >= 1 and stats["rollbacks"] >= 1
                          and not tr_rec.preempted),
        "device_kind": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
    }]


def _measure_jax_cpu_baseline() -> float:
    """Our own pipeline on 2 virtual CPU devices (BASELINE config 1 analog)."""
    code = (
        "import os; os.environ['JAX_PLATFORMS']='cpu';"
        "os.environ['XLA_FLAGS']=os.environ.get('XLA_FLAGS','')"
        "+' --xla_force_host_platform_device_count=2';"
        "import jax; jax.config.update('jax_platforms','cpu');"
        "import sys; sys.path.insert(0, %r);"
        "from simple_distributed_machine_learning_tpu.parallel.compat "
        "import set_host_device_count; set_host_device_count(2);"
        "from bench import measure, _configs;"
        "import json; spec = dict(_configs()['mlp2'], steps_override=2000);"
        "print('RESULT'+json.dumps(measure('mlp2', spec, windows=2)))"
        % REPO)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=600, cwd=REPO)
    for line in out.stdout.splitlines():
        if line.startswith("RESULT"):
            return json.loads(line[len("RESULT"):])["samples_per_sec"]
    raise RuntimeError(f"jax cpu baseline failed: {out.stderr[-2000:]}")


def _measure_torch_rpc_baseline() -> float:
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "torch_rpc_baseline.py")],
        capture_output=True, text=True, timeout=900, cwd=REPO)
    for line in out.stdout.splitlines():
        if line.startswith("RESULT"):
            return json.loads(line[len("RESULT"):])["samples_per_sec"]
    raise RuntimeError(f"torch rpc baseline failed: {out.stderr[-2000:]}")


def _apply_env_platform() -> None:
    """Honor JAX_PLATFORMS / xla_force_host_platform_device_count even when
    sitecustomize already imported jax and latched the TPU plugin (same shim
    as cli.py) — lets the bench run on virtual CPU devices for schedule
    smoke-tests. No-op in the driver's TPU invocation (no env override)."""
    import re

    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    import jax
    try:
        jax.config.update("jax_platforms", plat)
        m = re.search(r"xla_force_host_platform_device_count=(\d+)",
                      os.environ.get("XLA_FLAGS", ""))
        if m and plat == "cpu":
            from simple_distributed_machine_learning_tpu.parallel.compat import (
                set_host_device_count,
            )
            set_host_device_count(int(m.group(1)))
    except RuntimeError:
        pass


def main() -> None:
    _apply_env_platform()
    ap = argparse.ArgumentParser()
    ap.add_argument("--measure-baseline", action="store_true",
                    help="re-measure CPU baselines and rewrite "
                         "benchmarks/baseline_cpu.json")
    ap.add_argument("--all", action="store_true",
                    help="measure every config, one JSON line each, and "
                         "write benchmarks/results_all.json")
    ap.add_argument("--config", default=None,
                    choices=list(_configs()) + ["gpt_bf16_xl"],
                    help="single config to measure (default: headline mlp2; "
                         "with --decode and no --config, decode only)")
    ap.add_argument("--steps", type=int, default=None,
                    help="override the per-config scan-window length (use "
                         "when dispatch noise exceeds the window)")
    ap.add_argument("--schedule", choices=("gpipe", "1f1b"),
                    default="gpipe",
                    help="pipeline schedule to bench (1f1b engages only "
                         "with >= 2 pipeline stages, i.e. >= 2 chips)")
    ap.add_argument("--decode", action="store_true",
                    help="measure KV-cache vs recompute decode tokens/sec "
                         "(also runs as part of --all)")
    ap.add_argument("--serve", action="store_true",
                    help="offered-load serving sweep (serve/): continuous-"
                         "batching tokens/sec + TTFT/TPOT p50/p95 per "
                         "Poisson arrival rate, vs the 1-slot sequential "
                         "baseline; writes benchmarks/serving.json")
    ap.add_argument("--serve-kernel", choices=("dense", "fused"),
                    default="dense",
                    help="with --serve: the sweep engines' paged-attention "
                         "path — dense gather-then-dense (parity anchor) "
                         "or the fused Pallas flash-decode kernel; the "
                         "kernel comparison rows always measure both")
    ap.add_argument("--opt", choices=("sgd", "adamw"), default=None,
                    help="override the per-config optimizer (experiment "
                         "rows only; results_all.json is not rewritten "
                         "under an override)")
    ap.add_argument("--attn", choices=("dense", "flash"), default=None,
                    help="override the GPT rows' attention implementation "
                         "(whole-model flash-vs-dense comparison; "
                         "experiment rows only, like --opt)")
    ap.add_argument("--flash-blocks", type=str, default=None, metavar="Q,K",
                    help="with --attn flash: kernel block sizes")
    ap.add_argument("--lr", type=float, default=None,
                    help="override the optimizer learning rate (with "
                         "--opt sgd keeps momentum=0.5; experiment rows "
                         "only, like --opt)")
    ap.add_argument("--tp", type=int, default=None,
                    help="shard the GPT rows' blocks tensor-parallel over "
                         "this many devices (Megatron QKV/O + MLP; one "
                         "pipeline stage, the whole mesh to the model "
                         "axis; experiment rows only, like --opt)")
    ap.add_argument("--overlap", choices=("none", "ring"), default=None,
                    help="collective schedule for the GPT rows' tensor-"
                         "parallel all-reduces: none = monolithic psum, "
                         "ring = latency-hiding ppermute-chunked collective "
                         "matmuls (parallel/overlap.py); pair with --tp; "
                         "experiment rows only, like --opt")
    ap.add_argument("--sentinel", action="store_true",
                    help="self-healing training rows (resilience/"
                         "sentinel.py): sentinel on/off steps-per-sec "
                         "overhead plus a nan-grad recovery drill "
                         "(rollback + quarantine, anti-vacuous "
                         "faults_fired gate)")
    ap.add_argument("--lint", action="store_true",
                    help="static-analysis preflight (analysis/): lint the "
                         "exact scanned step of every row before timing it "
                         "(with --serve, the whole serving-program registry "
                         "on both KV layouts) and abort on ERROR findings")
    ap.add_argument("--smoke-probe", action="store_true",
                    help=argparse.SUPPRESS)  # the probe SUBPROCESS body
    args = ap.parse_args()
    if args.smoke_probe:
        _smoke_probe_main()
        return
    # mirror cli.py's validation instead of silently ignoring the flag or
    # dumping a raw ValueError traceback from the int parse
    if args.flash_blocks and args.attn != "flash":
        raise SystemExit("--flash-blocks needs --attn flash")
    if args.flash_blocks:
        raw = args.flash_blocks
        try:
            bq, bk = (int(v) for v in raw.split(","))
        except ValueError:
            raise SystemExit(
                f"--flash-blocks expects Q,K integers, got {raw!r}"
            ) from None
        args.flash_blocks = (bq, bk)
    if args.overlap == "ring" and args.tp is None:
        args.tp = 2          # smallest sharded row: the ring schedule
        #                      measures a collective, which needs a shard
    if args.tp is not None or args.overlap is not None:
        # flag-level spec validation through the analyzer preflight (device
        # count and model-shape divisibility re-checked per row in measure())
        from simple_distributed_machine_learning_tpu.analysis.preflight import (
            validate_tp_overlap,
        )
        errors, _ = validate_tp_overlap(args.tp if args.tp is not None else 1,
                                        args.overlap or "none")
        if errors:
            raise SystemExit("bench: invalid --tp/--overlap spec:\n  "
                             + "\n  ".join(errors))
    if (args.tp or args.overlap) and args.config is None and not args.all:
        args.config = "gpt"  # the TP/overlap axes are GPT-row knobs

    if args.measure_baseline or not os.path.exists(BASELINE_PATH):
        baselines = {}
        try:
            baselines["torch_rpc_cpu_samples_per_sec"] = \
                _measure_torch_rpc_baseline()
        except Exception as e:  # noqa: BLE001 - record and continue
            baselines["torch_rpc_cpu_error"] = str(e)[-500:]
        baselines["jax_cpu_pipeline_samples_per_sec"] = \
            _measure_jax_cpu_baseline()
        os.makedirs(os.path.dirname(BASELINE_PATH), exist_ok=True)
        with open(BASELINE_PATH, "w") as f:
            json.dump(baselines, f, indent=2)
    else:
        with open(BASELINE_PATH) as f:
            baselines = json.load(f)
    base = baselines.get("torch_rpc_cpu_samples_per_sec") or \
        baselines.get("jax_cpu_pipeline_samples_per_sec")

    configs = _configs()
    if args.config == "gpt_bf16_xl" and not args.all:
        # explicit opt-in only: never joins the --all sweep (slow compile,
        # heavy HBM; _xl_config's contract)
        configs["gpt_bf16_xl"] = _xl_config()
    # --decode is additive: an explicit --config still runs; only a bare
    # --decode (no --all, no --config) measures decode alone
    if args.all:
        names = list(configs)
    elif args.config is not None:
        names = [args.config]
    else:
        names = [] if (args.decode or args.serve or args.sentinel) \
            else ["mlp2"]
    # rc-17-aware preflight (SDML_CHAOS can inject wedged-device faults):
    # retry once with backoff; on persistent wedge the structured
    # device_unhealthy row IS this round's measurement — exit 0, no hang
    from simple_distributed_machine_learning_tpu.resilience.faults import (
        install_from_env,
    )
    install_from_env()
    if not _supervised_smoke():
        if args.serve:
            # the r04/r05 standing-note fix: a wedged device on a --serve
            # round leaves a STRUCTURED record in the serving artifact
            # (instead of a silently stale baseline or a measurement-less
            # death), so the next healthy round's real rows re-establish
            # the baseline automatically and the gap is attributable
            with open(os.path.join(REPO, "benchmarks", "serving.json"),
                      "w") as f:
                json.dump({"device_unhealthy": True, "rc": WEDGED_RC,
                           "detail": "accelerator unresponsive (wedged "
                                     "device/tunnel); serve sweep skipped",
                           "rows": []}, f, indent=2)
        return

    def _run_decode() -> None:
        # decode is the least-trusted measurement on a flaky tunnel (its
        # fail-loud dt<=0 guard can fire on one noisy window) — never let it
        # forfeit the train table
        try:
            drow = measure_decode()
            print(json.dumps({
                "metric": "gpt_decode_tokens_per_sec",
                "value": drow["tokens_per_sec_cached"],
                "unit": "tokens/sec",
                "vs_recompute": drow["speedup"],
            }))
        except Exception as e:  # noqa: BLE001 - record and continue
            sys.stderr.write(f"bench: decode measurement failed: {e}\n")
            if not args.all:
                raise

    if args.decode and not args.all:
        _run_decode()
    if args.sentinel:
        for srow in _measure_sentinel():
            print(json.dumps({"metric": srow.pop("config"), **srow}))
        if not names and not args.serve:
            return
    if args.serve:
        for srow in measure_serving(lint=args.lint,
                                    attn_kernel=args.serve_kernel):
            line = {"metric": srow["config"], "n_slots": srow["n_slots"]}
            # sweep rows report throughput+latency; the paged-vs-dense
            # comparison rows report concurrency / tick-latency instead
            for k in ("tokens_per_sec", "rate", "ttft_ms_p50",
                      "ttft_ms_p95", "tpot_ms_p50", "tpot_ms_p95",
                      "slot_occupancy_mean", "kv_bytes", "max_concurrent",
                      "long_prompt_len", "tick_ms_p50", "tick_ms_p95",
                      "tick_ms_max", "tp", "spec_k", "accept_rate",
                      "tokens_per_tick_spec", "tokens_per_tick_plain",
                      "speedup_vs_plain", "wall_tokens_per_sec_spec",
                      "wall_tokens_per_sec_plain", "kernel",
                      "ticks_per_sec", "decode_kv_bytes_per_tick",
                      "hbm_reduction", "streams_bit_exact", "cache_dtype",
                      "kv_budget_bytes", "n_blocks", "resident_ratio"):
                if srow.get(k) is not None:
                    line[k] = srow[k]
            print(json.dumps(line))
        if not names:
            return
    rows = []

    def _write_results(partial: bool) -> None:
        # the authoritative GPipe artifact — a 1f1b sweep writes its own
        # file instead of silently overwriting it with rows that used to be
        # indistinguishable. Both the filename and the top-level field
        # reflect what actually RAN, not what was requested: on one chip a
        # --schedule 1f1b sweep degenerates to gpipe rows (measure()'s
        # n_stages < 2 fallback) and is recorded as such. Written after
        # EVERY row (partial=True) so a late-row failure on flaky hardware
        # cannot cost the rows already measured.
        if not rows:
            return
        ran = {r["schedule"] for r in rows}
        sched_actual = ran.pop() if len(ran) == 1 else "mixed"
        if not partial and sched_actual != args.schedule:
            sys.stderr.write(
                f"bench: requested --schedule {args.schedule} but rows ran "
                f"{sched_actual} (single-chip fallback?); recording "
                f"{sched_actual}\n")
        path = (RESULTS_PATH if sched_actual == "gpipe" else
                RESULTS_PATH.replace(".json", f"_{sched_actual}.json"))
        # never let a CPU-backend sweep silently clobber the authoritative
        # TPU artifact (easy to do from a dev shell with JAX_PLATFORMS=cpu)
        if rows[0]["backend"] != "tpu" and os.path.exists(path):
            try:
                with open(path) as f:
                    prev = json.load(f)
            except Exception:
                prev = {}
            if prev.get("backend") == "tpu":
                path = path.replace(".json", f"_{rows[0]['backend']}.json")
                if partial is False:
                    sys.stderr.write(
                        f"bench: existing artifact is from TPU; this "
                        f"{rows[0]['backend']} sweep written to {path}\n")
        payload = {"device": rows[0]["device_kind"],
                   "backend": rows[0]["backend"],
                   "schedule": sched_actual,
                   "rows": rows}
        if partial:
            payload["partial"] = True
        with open(path, "w") as f:
            json.dump(payload, f, indent=2)

    write_artifact = (args.all and args.opt is None and args.lr is None
                      and args.attn is None and args.tp is None
                      and args.overlap is None)
    for name in names:
        spec = (dict(configs[name], steps_override=args.steps)
                if args.steps else configs[name])
        if (args.opt is not None or args.lr is not None
                or args.attn is not None or args.tp is not None
                or args.overlap is not None):
            spec = dict(spec)
            if args.opt is not None:
                spec["opt"] = args.opt
            if args.lr is not None:
                spec["lr"] = args.lr
            if args.attn is not None and spec["kind"] == "gpt":
                spec["attn"] = args.attn
                if args.flash_blocks:
                    spec["flash_blocks"] = args.flash_blocks
            if spec["kind"] == "gpt":
                if args.tp is not None:
                    spec["tp"] = args.tp
                if args.overlap is not None:
                    spec["overlap"] = args.overlap
        res = measure(name, spec, schedule=args.schedule, lint=args.lint)
        # vs_baseline only for the headline: the torch-RPC baseline runs the
        # 2-stage MLP workload, not the others
        vs = (round(res["samples_per_sec"] / base, 2)
              if base and name in ("mlp2", "mlp2_bf16") else None)
        rows.append(dict(res, vs_baseline=vs))
        print(json.dumps({
            "metric": f"{name}_samples_per_sec_per_chip"
                      if name != "mlp2" else
                      "2stage_mlp_pipeline_samples_per_sec_per_chip",
            "value": res["samples_per_sec_per_chip"],
            "unit": "samples/sec/chip",
            "vs_baseline": vs,
            "mfu": res["mfu"],
            "achieved_tflops": res["achieved_tflops"],
            "dtype": res["dtype"],
            "n_chips": res["n_chips"],
            "schedule": res["schedule"],
            "optimizer": res["optimizer"],
            "tp": res["tp"],
            "overlap": res["overlap"],
            # latency quantiles + bubble (telemetry/): p50/p95 say more than
            # a mean on a jittery tunnel; bubble ranks schedule headroom
            "step_ms_p50": res["step_ms_p50"],
            "step_ms_p95": res["step_ms_p95"],
            "bubble_fraction": res["bubble_fraction"],
            "ici_bytes_per_step": res["ici_bytes_per_step"],
        }))
        if write_artifact:
            _write_results(partial=True)
    if args.all:
        # decode runs AFTER the train table so a decode failure can never
        # cost the sweep its main payload
        _run_decode()
    if args.all and not write_artifact:
        sys.stderr.write(
            "bench: --opt/--lr override active - results_all.json NOT "
            "rewritten (experiment rows only)\n")
    elif write_artifact:
        _write_results(partial=False)


if __name__ == "__main__":
    main()
